"""Supervised execution (runtime/supervisor.py): restart strategies,
automatic crash recovery, poison-record quarantine, sink retry.

The reference tutorial ends on "TaskManager crashes mid-window?"
(chapter3/README.md:454-456); these tests pin the Flink-1.8 answer built
here: a deterministic injected fault (tpustream/testing/faults.py) kills
the job mid-stream, the configured restart strategy restarts it from the
latest auto-checkpoint, and the recovered run's sink output is
byte-identical to an uninterrupted run. Heavy sharded/soak variants live
in test_recovery_sharded.py (slow tier).
"""

import glob
import json
import os

import pytest

from tpustream import StreamExecutionEnvironment
from tpustream.config import ObsConfig, StreamConfig
from tpustream.runtime.checkpoint import (
    latest_checkpoint,
    load_checkpoint,
    validate_checkpoint,
)
from tpustream.runtime.sources import IterableSource, ReplaySource
from tpustream.runtime.supervisor import (
    RESTART_HEALTH_RULE_NAME,
    FailureRateRestart,
    FixedDelayRestart,
    NoRestart,
    RestartStrategies,
    failure_rate,
    fixed_delay,
    no_restart,
)
from tpustream.testing import FaultInjected, FaultInjector, FaultPoint, poison_lines

LINES = [
    "1563452056 10.8.22.1 cpu0 80.5",
    "1563452050 10.8.22.1 cpu0 78.4",
    "1563452056 10.8.22.2 cpu1 40.0",
    "1563452060 10.8.22.1 cpu0 99.9",
    "1563452061 10.8.22.2 cpu1 10.0",
    "1563452062 10.8.22.1 cpu0 50.0",
]


def run_supervised(
    items, build=None, ckdir=None, strategy=None, injector=None,
    source=None, **over
):
    """One job run; returns (env, collected items, JobResult)."""
    if build is None:
        from tpustream.jobs.chapter2_max import build
    over.setdefault("batch_size", 2)
    cfg = StreamConfig(**over)
    if ckdir is not None:
        cfg = cfg.replace(
            checkpoint_dir=str(ckdir), checkpoint_interval_batches=1
        )
    if injector is not None:
        cfg = injector.install(cfg)
    env = StreamExecutionEnvironment(cfg)
    if strategy is not None:
        env.set_restart_strategy(strategy)
    text = env.add_source(source if source is not None else ReplaySource(items))
    handle = build(env, text).collect()
    result = env.execute("recovery-test")
    return env, handle.items, result


# ---------------------------------------------------------------------------
# restart strategy decisions (pure host logic, Flink 1.8 parity)
# ---------------------------------------------------------------------------
def test_restart_strategy_decisions():
    assert no_restart().next_delay(0, [], 0.0) is None
    fd = fixed_delay(attempts=2, delay_s=1.5)
    assert fd.next_delay(0, [0.0], 0.0) == 1.5
    assert fd.next_delay(1, [0.0, 1.0], 1.0) == 1.5
    assert fd.next_delay(2, [0.0, 1.0, 2.0], 2.0) is None
    fr = failure_rate(max_failures=2, window_s=10.0, delay_s=0.5)
    # 2 failures inside the window: still under the rate -> restart
    assert fr.next_delay(1, [99.0, 100.0], 100.0) == 0.5
    # 3 recent failures exceed max_failures=2 -> give up
    assert fr.next_delay(2, [98.0, 99.0, 100.0], 100.0) is None
    # old failures age out of the window
    assert fr.next_delay(5, [1.0, 2.0, 99.0, 100.0], 100.0) == 0.5


def test_restart_strategies_factory_and_env_api():
    s = RestartStrategies.fixedDelayRestart(4, 2.0)
    assert isinstance(s, FixedDelayRestart)
    assert (s.attempts, s.delay_s) == (4, 2.0)
    assert isinstance(RestartStrategies.noRestart(), NoRestart)
    assert isinstance(
        RestartStrategies.failureRateRestart(1, 5.0, 0.1), FailureRateRestart
    )
    env = StreamExecutionEnvironment(StreamConfig())
    env.setRestartStrategy(s)  # Flink-style alias
    assert env.config.restart_strategy is s


# ---------------------------------------------------------------------------
# the tentpole: crash mid-stream, auto-restart, byte-identical output
# ---------------------------------------------------------------------------
def test_fixed_delay_recovery_exactly_once(tmp_path):
    """device_step fault at step 2 under fixed_delay: the job restarts
    from the latest auto-checkpoint and the collected output is
    byte-identical to an uninterrupted run. Asserts the full observable
    recovery story in one job: per-cause restart counter, replay/wall
    recovery series, checkpoint cost histograms, the flight-recorder
    failure->restart->restored->recovered sequence, and the built-in
    WARN health rule."""
    _, full, _ = run_supervised(LINES)
    inj = FaultInjector(FaultPoint("device_step", at=2))
    env, out, res = run_supervised(
        LINES, ckdir=tmp_path, strategy=fixed_delay(3, 0.0), injector=inj,
        obs=ObsConfig(enabled=True),
    )
    assert inj.fired == 1
    assert out == full, "recovered output must match an uninterrupted run"

    snap = res.metrics.obs_snapshot()
    series = snap["metrics"]["series"]
    restarts = [s for s in series if s["name"] == "job_restarts_total"]
    assert sum(s["value"] for s in restarts) == 1
    assert restarts[0]["labels"]["cause"] == "device_step"
    replay = next(s for s in series if s["name"] == "recovery_replay_batches")
    assert replay["value"] > 0
    names = {s["name"] for s in series}
    assert {"recovery_wall_ms", "checkpoint_save_ms", "checkpoint_bytes"} <= names

    kinds = [e["kind"] for e in res.metrics.job_obs.flight.events()]
    for want in (
        "job_failed", "job_restarting", "job_restored", "job_recovered"
    ):
        assert want in kinds, f"missing flight event {want}: {kinds}"
    assert kinds.index("job_failed") < kinds.index("job_restarting")
    assert kinds.index("job_restarting") < kinds.index("job_restored")

    health = snap["health"]
    rule = next(
        r for r in health["rules"] if r["rule"] == RESTART_HEALTH_RULE_NAME
    )
    assert rule["level"] == "warn"


def test_every_fault_point_recovers(tmp_path):
    """source_read / parse / sink_emit faults all restart-and-recover to
    identical output (device_step is the tentpole test above; exchange
    needs a mesh, test_recovery_sharded.py)."""
    _, full, _ = run_supervised(LINES)
    for point, at in (("source_read", 2), ("parse", 2), ("sink_emit", 3)):
        inj = FaultInjector(FaultPoint(point, at=at))
        _, out, _ = run_supervised(
            LINES, ckdir=tmp_path / point, strategy=fixed_delay(3, 0.0),
            injector=inj,
        )
        assert inj.fired == 1, point
        assert out == full, f"{point} recovery diverged"


def test_deep_pipeline_fault_recovery_exactly_once(tmp_path):
    """device_step and sink_emit faults injected while the async
    pipeline is several batches deep — staged H2D uploads (h2d_depth),
    a deep dispatch queue (async_depth), grouped count fetches, and
    device-side compaction all in flight — must still recover
    exactly-once from the latest checkpoint."""
    lines = [
        f"15634520{i:02d} 10.8.22.{i % 3} cpu{i % 2} {40 + (i * 17) % 55}.5"
        for i in range(16)
    ]
    deep = dict(
        async_depth=4, h2d_depth=3, fetch_group=2, compaction_capacity=64
    )
    _, sync_ref, _ = run_supervised(lines)
    _, full, _ = run_supervised(lines, **deep)
    assert full == sync_ref  # the pipeline itself is invisible
    for point, at in (("device_step", 4), ("sink_emit", 5)):
        inj = FaultInjector(FaultPoint(point, at=at))
        _, out, _ = run_supervised(
            lines, ckdir=tmp_path / point, strategy=fixed_delay(3, 0.0),
            injector=inj, **deep,
        )
        assert inj.fired == 1, point
        assert out == full, f"{point} deep-pipeline recovery diverged"


def test_scratch_restart_without_checkpoints():
    """No checkpoint dir: the supervisor rolls collected output back to
    the pre-job baseline and replays from scratch — still exactly-once."""
    _, full, _ = run_supervised(LINES)
    inj = FaultInjector(FaultPoint("device_step", at=2))
    _, out, _ = run_supervised(LINES, strategy=fixed_delay(3, 0.0), injector=inj)
    assert inj.fired == 1
    assert out == full


def test_fixed_delay_gives_up_after_attempts(tmp_path):
    """A persistent fault exhausts fixed_delay(2): two restarts, then
    the third failure propagates."""
    inj = FaultInjector(FaultPoint("device_step", at=1, times=1000))
    with pytest.raises(FaultInjected):
        run_supervised(
            LINES, ckdir=tmp_path, strategy=fixed_delay(2, 0.0), injector=inj
        )
    assert inj.fired == 3  # initial attempt + 2 restarts


def test_no_restart_fails_fast_with_flight_dump(tmp_path):
    dump = tmp_path / "postmortem.json"
    inj = FaultInjector(FaultPoint("device_step", at=2))
    with pytest.raises(FaultInjected):
        run_supervised(
            LINES, ckdir=tmp_path / "ck", strategy=no_restart(), injector=inj,
            obs=ObsConfig(enabled=True, flight_dump_path=str(dump)),
        )
    assert inj.fired == 1
    assert dump.exists(), "failure must leave the postmortem dump"
    events = json.loads(dump.read_text())["events"]
    kinds = [e["kind"] for e in events]
    assert "exception" in kinds
    assert "job_not_restarting" in kinds  # the supervision decision


def test_non_replayable_source_refuses_restart():
    """A consumed-iterator source cannot re-yield the stream: the
    supervisor refuses the restart (flight breadcrumb) and fails."""
    inj = FaultInjector(FaultPoint("device_step", at=2))
    with pytest.raises(FaultInjected):
        run_supervised(
            LINES, strategy=fixed_delay(3, 0.0), injector=inj,
            source=IterableSource(iter(LINES)),
        )
    assert inj.fired == 1  # no second attempt ever ran


# ---------------------------------------------------------------------------
# poison-record quarantine (StreamConfig.dead_letter)
# ---------------------------------------------------------------------------
def test_poison_quarantine_chapter1():
    """Poison lines in the chapter-1 threshold input land in the
    dead-letter output with correct counts; the clean records' output is
    unchanged."""
    from tpustream.jobs.chapter1_threshold import build

    clean = [
        "1563452051 10.8.22.1 cpu2 10.5",
        "1563452051 10.8.22.1 cpu2 99.2",
        "1563452052 10.8.22.3 cpu1 95.0",
    ]
    _, want, _ = run_supervised(clean, build=build)
    poisoned, n = poison_lines(clean, count=2, seed=7)
    env, out, res = run_supervised(poisoned, build=build, dead_letter=True)
    assert out == want
    assert n == 2 and len(env.dead_letters) == 2
    assert res.summary()["records_quarantined"] == 2
    for line, err in env.dead_letters:
        assert "poison" in line and err  # (line, reason) pairs


def test_poison_quarantine_chapter3_eventtime():
    from tpustream import TimeCharacteristic
    from tpustream.jobs.chapter3_bandwidth_eventtime import build

    clean = [
        "2019-08-28T09:00:00 www.163.com 1000",
        "2019-08-28T09:02:00 www.163.com 2000",
        "2019-08-28T09:03:00 www.163.com 3000",
        "2019-08-28T09:05:00 www.163.com 4000",
        "2019-08-28T09:07:00 www.163.com 500",
    ]

    def run(items, **kw):
        env = StreamExecutionEnvironment(
            StreamConfig(batch_size=2, **kw)
        )
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        text = env.add_source(ReplaySource(items))
        handle = build(env, text).collect()
        env.execute("ch3-poison")
        return env, handle.items

    _, want = run(clean)
    poisoned, n = poison_lines(clean, count=3, seed=3)
    env, out = run(poisoned, dead_letter=True)
    assert out == want
    assert len(env.dead_letters) == n == 3


def test_quarantine_capacity_bounds_dead_letters():
    """dead_letter_capacity bounds the retained lines; the counter keeps
    the true total."""
    clean = list(LINES)
    poisoned, n = poison_lines(clean, count=3, seed=5)
    env, out, res = run_supervised(
        poisoned, dead_letter=True, dead_letter_capacity=1
    )
    _, want, _ = run_supervised(clean)
    assert out == want
    assert len(env.dead_letters) == 1
    assert res.summary()["records_quarantined"] == n == 3


def test_injected_parse_fault_escalates_past_quarantine():
    """Quarantine is for poison DATA; an injected parse fault models a
    crash and must escalate even with dead_letter on."""
    inj = FaultInjector(FaultPoint("parse", at=2))
    with pytest.raises(FaultInjected):
        run_supervised(LINES, dead_letter=True, injector=inj)


def test_quarantine_survives_restart(tmp_path):
    """Poison + a crash: the recovered run neither duplicates nor loses
    dead-letter records (they roll back with the sink outputs)."""
    clean = [
        f"15634520{i:02d} 10.8.22.{i % 3} cpu0 {50 + (i * 31) % 47}.5"
        for i in range(8)
    ]
    _, want, _ = run_supervised(clean)
    poisoned, n = poison_lines(clean, count=2, seed=11)
    inj = FaultInjector(FaultPoint("device_step", at=2))
    env, out, res = run_supervised(
        poisoned, ckdir=tmp_path, strategy=fixed_delay(3, 0.0), injector=inj,
        dead_letter=True,
    )
    assert inj.fired == 1
    assert out == want
    assert len(env.dead_letters) == n == 2
    assert res.summary()["records_quarantined"] == 2


# ---------------------------------------------------------------------------
# sink retry with capped exponential backoff
# ---------------------------------------------------------------------------
def test_sink_retry_recovers_transient_failure():
    """A sink_emit fault firing twice is absorbed by sink_retries=3 —
    no restart, identical output."""
    _, full, _ = run_supervised(LINES)
    inj = FaultInjector(FaultPoint("sink_emit", at=1, times=2))
    _, out, _ = run_supervised(
        LINES, injector=inj, sink_retries=3, sink_retry_base_ms=0.0
    )
    assert inj.fired == 2  # both injected failures were retried through
    assert out == full


def test_sink_failure_escalates_without_retries():
    inj = FaultInjector(FaultPoint("sink_emit", at=2))
    with pytest.raises(FaultInjected):
        run_supervised(LINES, injector=inj)


def test_sink_retry_backoff_is_capped():
    from tpustream.runtime.sinks import RetryingSink

    class Flaky:
        obs_counter = None
        fails = 3

        def __init__(self):
            self.got = []

        def emit(self, value, subtask=None):
            if self.fails:
                self.fails -= 1
                raise RuntimeError("transient")
            self.got.append(value)

    import time

    inner = Flaky()
    sink = RetryingSink(inner, attempts=3, base_ms=1.0, max_ms=2.0)
    t0 = time.perf_counter()
    sink.emit("v")
    # delays 1ms, 2ms, 2ms (capped) — far below an uncapped 1+2+4
    assert time.perf_counter() - t0 < 0.5
    assert inner.got == ["v"]
    # exhausting attempts re-raises the sink error
    inner.fails = 99
    with pytest.raises(RuntimeError, match="transient"):
        sink.emit("w")


# ---------------------------------------------------------------------------
# checkpoint hardening (satellites 1+2): atomic writes, checksums,
# skipping partial/corrupt/incompatible snapshots
# ---------------------------------------------------------------------------
def _snaps(d):
    return sorted(glob.glob(os.path.join(str(d), "ckpt-*.npz")))


def test_corrupt_checkpoint_detected_and_skipped(tmp_path):
    run_supervised(LINES, ckdir=tmp_path)
    snaps = _snaps(tmp_path)
    assert len(snaps) >= 2
    newest = snaps[-1]
    # flip payload bytes near the end (past the metadata header)
    blob = bytearray(open(newest, "rb").read())
    blob[-64:-32] = bytes(32)
    with open(newest, "wb") as f:
        f.write(blob)
    reason = validate_checkpoint(newest)
    assert reason is not None and ("checksum" in reason or "unreadable" in reason)
    with pytest.raises((ValueError, Exception)):
        load_checkpoint(newest)

    class Ring:
        def __init__(self):
            self.events = []

        def record(self, kind, **payload):
            self.events.append((kind, payload))

    ring = Ring()
    picked = latest_checkpoint(str(tmp_path), flight=ring)
    assert picked in snaps and picked != newest
    assert validate_checkpoint(picked) is None
    assert any(
        k == "checkpoint_skipped" and p["path"] == newest
        for k, p in ring.events
    )


def test_partial_and_foreign_files_skipped(tmp_path):
    run_supervised(LINES, ckdir=tmp_path)
    snaps = _snaps(tmp_path)
    # a torn write that sorts NEWEST (and is named into the marker)
    partial = os.path.join(str(tmp_path), "ckpt-9999999999.npz")
    with open(partial, "wb") as f:
        f.write(b"PK\x03\x04 torn write")
    with open(os.path.join(str(tmp_path), "latest"), "w") as f:
        f.write(os.path.basename(partial))
    picked = latest_checkpoint(str(tmp_path))
    assert picked == snaps[-1]  # newest VALID snapshot, not the torn file


def test_recovery_prefers_newest_valid_snapshot(tmp_path):
    """End to end: corrupt the newest snapshot, crash the job — the
    restart restores from the older valid one and output still matches."""
    _, full, _ = run_supervised(LINES)

    # seed the dir with snapshots, then corrupt the newest
    run_supervised(LINES, ckdir=tmp_path)
    newest = _snaps(tmp_path)[-1]
    blob = bytearray(open(newest, "rb").read())
    blob[-64:-32] = bytes(32)
    with open(newest, "wb") as f:
        f.write(blob)

    inj = FaultInjector(FaultPoint("device_step", at=2))
    env, out, res = run_supervised(
        LINES, ckdir=tmp_path, strategy=fixed_delay(3, 0.0), injector=inj,
        obs=ObsConfig(enabled=True),
    )
    assert inj.fired == 1
    assert out == full
    # note: the crashed attempt usually re-saved a valid snapshot at the
    # corrupt name before failing; the breadcrumb only appears when the
    # corrupt file actually survived to restart time. Either way the
    # recovered output above is the contract.


def test_checkpoint_meta_records_recovery_fields(tmp_path):
    run_supervised(LINES, ckdir=tmp_path)
    ck = load_checkpoint(_snaps(tmp_path)[-1])
    assert ck.sink_counts is not None and len(ck.sink_counts) == 1
    assert ck.sink_counts[0] == ck.emitted  # single collect sink
    assert ck.quarantined == 0
    assert ck.session is None  # written outside supervision


def _rewrite_format_version(path, version):
    """Rewrite a snapshot's meta version in place (payload untouched, so
    the checksum stays valid — ONLY the format version mismatches),
    simulating a snapshot written by a pre-bump build."""
    import numpy as np

    from tpustream.runtime.checkpoint import _META_KEY

    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
        meta = json.loads(bytes(z[_META_KEY]).decode())
    meta["version"] = version
    with open(path, "wb") as f:
        np.savez(f, **arrays, **{_META_KEY: np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)})


def test_mixed_version_directory_skips_older_format(tmp_path):
    """A checkpoint directory straddling a format bump (regression for
    the v9 dynamic-rules bump): ``latest_checkpoint`` must treat
    current-FORMAT_VERSION snapshots as valid while skipping the
    older-version ones with a ``checkpoint_skipped`` breadcrumb — never
    handing the supervisor an unloadable path."""
    from tpustream.runtime.checkpoint import FORMAT_VERSION

    run_supervised(LINES, ckdir=tmp_path)
    snaps = _snaps(tmp_path)
    assert len(snaps) >= 2
    newest, older = snaps[-1], snaps[-2]
    # this build's snapshots ARE the current format — valid as written
    assert validate_checkpoint(newest) is None
    _rewrite_format_version(newest, FORMAT_VERSION - 1)
    reason = validate_checkpoint(newest)
    assert reason is not None and "version" in reason

    class Ring:
        def __init__(self):
            self.events = []

        def record(self, kind, **payload):
            self.events.append((kind, payload))

    ring = Ring()
    picked = latest_checkpoint(str(tmp_path), flight=ring)
    assert picked == older
    assert validate_checkpoint(picked) is None
    assert any(
        k == "checkpoint_skipped"
        and p["path"] == newest
        and "version" in p["reason"]
        for k, p in ring.events
    )


def test_recovery_survives_mixed_version_directory(tmp_path):
    """End to end: the newest snapshot is from an older format (a
    pre-upgrade run left it behind), the job crashes — the restart
    restores from the newest CURRENT-version snapshot and the output is
    still byte-identical to an uninterrupted run."""
    from tpustream.runtime.checkpoint import FORMAT_VERSION

    _, full, _ = run_supervised(LINES)
    run_supervised(LINES, ckdir=tmp_path)
    _rewrite_format_version(_snaps(tmp_path)[-1], FORMAT_VERSION - 1)

    inj = FaultInjector(FaultPoint("device_step", at=2))
    _, out, _ = run_supervised(
        LINES, ckdir=tmp_path, strategy=fixed_delay(3, 0.0), injector=inj,
    )
    assert inj.fired == 1
    assert out == full


def test_mixed_version_directory_skips_newer_format(tmp_path):
    """The straddle in the OTHER direction (regression for the v10
    tenancy bump): a snapshot written by a newer build — e.g. v10 with
    tenancy meta — must be skipped by this reader with a
    ``checkpoint_skipped`` breadcrumb, restoring from the newest
    snapshot this build can actually load. A rolled-back binary must
    never crash on its successor's checkpoints."""
    from tpustream.runtime.checkpoint import FORMAT_VERSION

    run_supervised(LINES, ckdir=tmp_path)
    snaps = _snaps(tmp_path)
    assert len(snaps) >= 2
    newest, older = snaps[-1], snaps[-2]
    _rewrite_format_version(newest, FORMAT_VERSION + 1)
    reason = validate_checkpoint(newest)
    assert reason is not None and "version" in reason

    class Ring:
        def __init__(self):
            self.events = []

        def record(self, kind, **payload):
            self.events.append((kind, payload))

    ring = Ring()
    picked = latest_checkpoint(str(tmp_path), flight=ring)
    assert picked == older
    assert validate_checkpoint(picked) is None
    assert any(
        k == "checkpoint_skipped"
        and p["path"] == newest
        and "version" in p["reason"]
        for k, p in ring.events
    )

    # and end to end: crash with the future-format snapshot newest —
    # the restart restores from the older current-format one, output
    # byte-identical to an uninterrupted run
    _, full, _ = run_supervised(LINES)
    inj = FaultInjector(FaultPoint("device_step", at=2))
    _, out, _ = run_supervised(
        LINES, ckdir=tmp_path, strategy=fixed_delay(3, 0.0), injector=inj,
    )
    assert inj.fired == 1
    assert out == full
