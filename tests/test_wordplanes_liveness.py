"""Unit tests for the window-state storage layers added for TPU speed:
int32 word-plane packing (ops/wordplanes.py), jaxpr liveness analysis
(ops/liveness.py), and the scatter-reduce fast path's end-to-end
equivalence with the exact sorted-merge path."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpustream.ops import liveness
from tpustream.ops.wordplanes import pack_words, plane_dtypes, unpack_words


def test_wordplane_roundtrip_exact():
    rng = np.random.default_rng(0)
    i64 = jnp.asarray(rng.integers(-(2**62), 2**62, 512))
    f64 = jnp.asarray(rng.standard_normal(512) * 1e30)
    s32 = jnp.asarray(rng.integers(0, 2**31 - 1, 512).astype(np.int32))
    b = jnp.asarray(rng.integers(0, 2, 512).astype(bool))
    kinds = ["i64", "f64", "str", "bool"]
    words = pack_words([i64, f64, s32, b], kinds)
    assert [w.dtype for w in words] == [
        jnp.int32, jnp.int32, jnp.float64, jnp.int32, jnp.int32
    ]
    back = unpack_words(words, kinds)
    assert np.array_equal(np.asarray(back[0]), np.asarray(i64))
    assert np.array_equal(np.asarray(back[1]), np.asarray(f64))
    assert np.array_equal(np.asarray(back[2]), np.asarray(s32))
    assert np.array_equal(np.asarray(back[3]), np.asarray(b))


def test_wordplane_compact32():
    kinds = ["i64", "f64"]
    assert [d.name for d in plane_dtypes(kinds, compact32=True)] == [
        "int32", "float32"
    ]
    vals = [jnp.asarray([5, -7]), jnp.asarray([1.5, -2.25])]
    words = pack_words(vals, kinds, compact32=True)
    back = unpack_words(words, kinds, compact32=True)
    assert np.array_equal(np.asarray(back[0]), [5, -7])
    assert np.array_equal(np.asarray(back[1]), [1.5, -2.25])


def test_liveness_fixpoint_and_passthrough():
    # ch3-shaped reduce: f0 first-seen, f1 key passthrough, f2 summed;
    # the post chain reads only (f1, f2)
    def combine(a0, a1, a2, b0, b1, b2):
        return (a0, a1, a2 + b2)

    def result(a0, a1, a2):
        return (a1, a2 * 8.0 / 60 / 1024 / 1024)

    d = [
        jnp.asarray(0, jnp.int64),
        jnp.asarray(0, jnp.int32),
        jnp.asarray(0, jnp.int64),
    ]
    live = liveness.live_accumulator_leaves(result, combine, d, 3)
    assert live == [False, True, True]
    assert liveness.passthrough_outputs(combine, d + d, 3) == [
        True, True, False
    ]
    assert liveness.leaf_algebraic_ops(combine, d, 3) == [
        "first", "first", "add"
    ]


def test_liveness_closure_pulls_combiner_deps():
    # the live output depends on a leaf the post chain never reads:
    # closure must mark it live
    def combine(a0, a1, b0, b1):
        return (a0 + b0, a1 + b1 + b0)

    def result(a0, a1):
        return (a1,)

    d = [jnp.asarray(0, jnp.int64), jnp.asarray(0, jnp.int64)]
    live = liveness.live_accumulator_leaves(result, combine, d, 2)
    assert live == [True, True]
    # a1's combine is NOT a plain add of (a1, b1)
    assert liveness.leaf_algebraic_ops(combine, d, 2) == ["add", None]


def _build_ch3(acc_dtype):
    from tpustream import StreamExecutionEnvironment, TimeCharacteristic
    from tpustream.config import StreamConfig
    from tpustream.jobs.chapter3_bandwidth_eventtime import build
    from tpustream.runtime.plan import build_plan
    from tpustream.runtime.sources import ReplaySource
    from tpustream.runtime.step import build_program

    cfg = StreamConfig(
        batch_size=256, key_capacity=32, alert_capacity=128, acc_dtype=acc_dtype
    )
    env = StreamExecutionEnvironment(cfg)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource([]))
    build(env, text).collect()
    plan = build_plan(env, env._sinks)
    return build_program(plan, cfg)


def test_fast_reduce_path_matches_exact_path():
    progs = {d: _build_ch3(d) for d in ("float64", "int32")}
    assert progs["int32"].fast_reduce and not progs["float64"].fast_reduce
    # the flagship's liveness result: only the flow sum is stored
    assert progs["int32"].stored_kinds == ["i64"]
    assert progs["int32"].key_leaf == 1

    base = 1_566_957_600_000
    outs = {}
    for d, prog in progs.items():
        state = prog.init_state()
        step = jax.jit(prog._step)
        rng = np.random.default_rng(3)
        rows = []
        for it in range(25):
            ts = base + it * 4000 + rng.integers(0, 9000, 256)
            keys = rng.integers(0, 32, 256).astype(np.int32)
            flow = rng.integers(1, 10_000, 256)
            cols = (
                jnp.asarray(ts // 1000),
                jnp.asarray(keys),
                jnp.asarray(flow),
            )
            state, em = step(
                state,
                cols,
                jnp.ones(256, bool),
                jnp.asarray(ts),
                jnp.asarray(-(2**62), jnp.int64),
            )
            m = np.asarray(em["main"]["mask"])
            for j in np.nonzero(m)[0]:
                rows.append(
                    (
                        int(np.asarray(em["main"]["cols"][0])[j]),
                        float(np.asarray(em["main"]["cols"][1])[j]),
                        int(np.asarray(em["main"]["window_end"])[j]),
                    )
                )
        outs[d] = sorted(rows)
    assert outs["float64"] == outs["int32"]
    assert len(outs["float64"]) > 0


def test_deferred_fires_drain_in_order():
    # budget 1 fire per step: a watermark jump spanning several slide
    # boundaries must fire them one per step, in end order, and count
    # the remainder in pending_fires
    from tpustream import StreamExecutionEnvironment, TimeCharacteristic
    from tpustream.config import StreamConfig
    from tpustream.jobs.chapter3_bandwidth_eventtime import build
    from tpustream.runtime.plan import build_plan
    from tpustream.runtime.sources import ReplaySource
    from tpustream.runtime.step import build_program

    cfg = StreamConfig(
        batch_size=64,
        key_capacity=8,
        alert_capacity=64,
        max_fires_per_step=1,
    )
    env = StreamExecutionEnvironment(cfg)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource([]))
    build(env, text).collect()
    plan = build_plan(env, env._sinks)
    prog = build_program(plan, cfg)

    base = 1_566_957_600_000
    state = prog.init_state()
    step = jax.jit(prog._step)
    ts = np.full(64, base, np.int64)
    cols = (
        jnp.asarray(ts // 1000),
        jnp.zeros(64, jnp.int32),
        jnp.full(64, 100, jnp.int64),
    )
    wm_jump = jnp.asarray(base + 3 * 5_000 + 1, jnp.int64)
    state, em = step(
        state, cols, jnp.ones(64, bool), jnp.asarray(ts), wm_jump
    )
    ends = []
    if int(np.asarray(em["main"]["mask"]).sum()):
        ends.append(int(np.asarray(em["main"]["window_end"])[0]))
    pending = int(np.asarray(state["pending_fires"]))
    assert pending > 0
    empty = (
        jnp.zeros(64, jnp.int64),
        jnp.zeros(64, jnp.int32),
        jnp.zeros(64, jnp.int64),
    )
    for _ in range(pending + 1):
        state, em = step(
            state, empty, jnp.zeros(64, bool), jnp.zeros(64, jnp.int64), wm_jump
        )
        m = np.asarray(em["main"]["mask"])
        if m.sum():
            ends.append(int(np.asarray(em["main"]["window_end"])[0]))
    assert int(np.asarray(state["pending_fires"])) == 0
    assert ends == sorted(ends) and len(ends) >= 2


def test_rolling_compact32_keeps_passthrough_fields_exact():
    """acc_dtype=int32 on a rolling max must truncate NOTHING but the
    aggregated column — kept first-record fields (which can be 64-bit
    timestamps) stay exact."""
    from tpustream.ops.rolling import (
        init_rolling_state,
        make_combiner,
        rolling_step,
    )

    kinds = ["i64", "str", "f64"]   # big ts, key id, aggregated usage
    combine = make_combiner("max", 2)
    compact = [False, False, True]  # what RollingProgram._compact32 yields
    state = init_rolling_state(16, kinds, compact)
    big_ts = 1_566_208_860_123_456  # > 2^32: wraps if wrongly compacted
    keys = jnp.asarray([3, 3], jnp.int32)
    cols = (
        jnp.asarray([big_ts, big_ts + 1], jnp.int64),
        jnp.asarray([3, 3], jnp.int32),
        jnp.asarray([80.5, 78.4], jnp.float64),
    )
    state, emis_sorted, sv, sk, inv = rolling_step(
        state, keys, cols, jnp.ones(2, bool), combine, kinds, compact
    )
    inv = np.asarray(inv)
    emis = [np.asarray(e)[inv] for e in emis_sorted]
    # first-record ts kept exactly for both emissions; max field rolls
    assert emis[0].tolist() == [big_ts, big_ts]
    assert emis[2].tolist() == [80.5, 80.5]
    # and the aggregated plane is stored 32-bit while ts planes are not
    assert state["planes"][0].dtype == jnp.int32   # ts lo
    assert state["planes"][1].dtype == jnp.int32   # ts hi
    assert state["planes"][3].dtype == jnp.float32  # compacted usage


def test_aggregate_fast_path_matches_exact_approximately():
    """The windowed-average AGGREGATE (acc = count int64 + sum float64,
    both algebraic adds) takes the scatter-reduce fast path under
    acc_dtype=float32; results match the exact path to f32 precision."""
    from tpustream import StreamExecutionEnvironment
    from tpustream.config import StreamConfig
    from tpustream.jobs.chapter2_avg import build
    from tpustream.runtime.plan import build_plan
    from tpustream.runtime.sources import AdvanceProcessingTime, ReplaySource
    from tpustream.runtime.step import build_program

    rng = np.random.default_rng(11)
    lines = [
        f"{1566208860 + i} 10.8.22.{i % 5} cpu{i % 3} "
        f"{rng.integers(1, 1000) / 10.0}"
        for i in range(400
        )
    ] + [AdvanceProcessingTime(300_000)]

    def run(acc_dtype):
        cfg = StreamConfig(batch_size=64, key_capacity=16, acc_dtype=acc_dtype)
        env = StreamExecutionEnvironment(cfg)
        text = env.add_source(ReplaySource(lines))
        h = build(env, text).collect()
        prog = build_program(build_plan(env, env._sinks), cfg)
        env.execute("avg")
        return sorted(float(x) for x in h.items), prog

    exact, p_exact = run("float64")
    fast, p_fast = run("float32")
    assert not p_exact.fast_reduce and p_fast.fast_reduce
    assert len(exact) == len(fast) > 0
    np.testing.assert_allclose(fast, exact, rtol=1e-5)
