"""Regenerate the checked-in checkpoint-format golden fixtures.

Runs GOLDEN_JOB (a tiny deterministic checkpointed chapter-2 rolling
max) and keeps its final snapshot in both v12 forms plus a version
ladder:

* ``ckpt-fv12.npz`` — the INLINE self-contained form, exactly as this
  build writes it with ``checkpoint_incremental=False``
* ``ckpt-fv12m.npz`` + ``chunks/`` — the INCREMENTAL manifest form:
  the npz holds only ``__meta__``; every leaf lives in a content-hash
  chunk file the manifest references (only the chunks the final
  manifest needs are kept)
* ``ckpt-fv08.npz`` … ``ckpt-fv11.npz`` — the inline payload with the
  meta version rewritten down (the ``_rewrite_format_version``
  technique from tests/test_recovery.py: payload and checksum stay
  valid, ONLY the format version mismatches — simulating snapshots
  written by older builds)
* ``ckpt-fv13.npz`` — a version this build does not know yet

tests/test_schema_audit.py asserts the state-layout auditor's verdict
on each fixture matches what ``validate_checkpoint`` /
``latest_checkpoint`` / a real restore actually do. Regenerate (only
needed after a deliberate FORMAT_VERSION bump) with::

    JAX_PLATFORMS=cpu python tests/goldens/make_checkpoint_goldens.py
"""

import glob
import json
import os
import shutil
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))

# enough lines for several batch_size=2 interval-1 snapshots
LINES = [
    f"15634520{i % 60:02d} 10.8.22.{i % 3} cpu{i % 2} {(i * 7) % 100}.5"
    for i in range(12)
]


def build_env(ckdir, incremental):
    """The golden job: chapter-2 rolling max over a replay source, one
    snapshot per batch. Must stay byte-stable across regenerations
    (checkpoint_async=False: the barrier writes inline, so the run's
    final snapshot is always the last batch's)."""
    from tpustream import StreamExecutionEnvironment
    from tpustream.config import StreamConfig
    from tpustream.jobs.chapter2_max import build

    env = StreamExecutionEnvironment(StreamConfig(
        batch_size=2,
        checkpoint_dir=str(ckdir),
        checkpoint_interval_batches=1,
        checkpoint_async=False,
        checkpoint_incremental=incremental,
    ))
    build(env, env.from_collection(LINES)).collect()
    return env


def rewrite_format_version(path, version):
    import numpy as np

    from tpustream.runtime.checkpoint import _META_KEY

    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
        meta = json.loads(bytes(z[_META_KEY]).decode())
    meta["version"] = version
    with open(path, "wb") as f:
        np.savez(f, **arrays, **{_META_KEY: np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)})


def _final_snapshot(ckdir):
    return sorted(glob.glob(os.path.join(ckdir, "ckpt-*.npz")))[-1]


def main():
    from tpustream.runtime.checkpoint import CHUNK_DIR, FORMAT_VERSION

    assert FORMAT_VERSION == 12, (
        f"FORMAT_VERSION moved to {FORMAT_VERSION}: re-point the fixture "
        "names/versions below and update tests/test_schema_audit.py"
    )
    # inline self-contained form + the version ladder derived from it
    d = tempfile.mkdtemp()
    build_env(d, incremental=False).execute("golden-checkpoint")
    current = os.path.join(HERE, "ckpt-fv12.npz")
    shutil.copy(_final_snapshot(d), current)
    for v in (8, 9, 10, 11, 13):
        p = os.path.join(HERE, f"ckpt-fv{v:02d}.npz")
        shutil.copy(current, p)
        rewrite_format_version(p, v)
    # incremental manifest form: the same job's final snapshot plus the
    # content-hash chunks its manifest references (and nothing else)
    d2 = tempfile.mkdtemp()
    build_env(d2, incremental=True).execute("golden-checkpoint")
    manifest = _final_snapshot(d2)
    shutil.copy(manifest, os.path.join(HERE, "ckpt-fv12m.npz"))
    import numpy as np

    from tpustream.runtime.checkpoint import _META_KEY

    with np.load(manifest) as z:
        meta = json.loads(bytes(z[_META_KEY]).decode())
    chunk_dst = os.path.join(HERE, CHUNK_DIR)
    shutil.rmtree(chunk_dst, ignore_errors=True)
    os.makedirs(chunk_dst)
    for ref in meta["chunks"]:
        name = f"{ref['chunk']}.npy"
        shutil.copy(
            os.path.join(d2, CHUNK_DIR, name),
            os.path.join(chunk_dst, name),
        )
    for n in sorted(os.listdir(HERE)):
        p = os.path.join(HERE, n)
        if n.endswith(".npz"):
            print(n, os.path.getsize(p), "bytes")
        elif os.path.isdir(p):
            print(f"{n}/ ({len(os.listdir(p))} chunks)")


if __name__ == "__main__":
    main()
