"""Regenerate the checked-in checkpoint-format golden fixtures.

Runs GOLDEN_JOB (a tiny deterministic checkpointed chapter-2 rolling
max) and keeps its final snapshot four ways:

* ``ckpt-fv10.npz`` — exactly as this build writes it (FORMAT_VERSION)
* ``ckpt-fv08.npz`` / ``ckpt-fv09.npz`` — the same payload with the
  meta version rewritten down (the ``_rewrite_format_version``
  technique from tests/test_recovery.py: payload and checksum stay
  valid, ONLY the format version mismatches — simulating a snapshot
  written by the pre-supervision / pre-dynamic-rules builds)
* ``ckpt-fv11.npz`` — a version this build does not know yet

tests/test_schema_audit.py asserts the state-layout auditor's verdict
on each fixture matches what ``validate_checkpoint`` /
``latest_checkpoint`` / a real restore actually do. Regenerate (only
needed after a deliberate FORMAT_VERSION bump) with::

    JAX_PLATFORMS=cpu python tests/goldens/make_checkpoint_goldens.py
"""

import glob
import json
import os
import shutil
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))

# enough lines for several batch_size=2 interval-1 snapshots
LINES = [
    f"15634520{i % 60:02d} 10.8.22.{i % 3} cpu{i % 2} {(i * 7) % 100}.5"
    for i in range(12)
]


def build_env(ckdir):
    """The golden job: chapter-2 rolling max over a replay source, one
    snapshot per batch. Must stay byte-stable across regenerations."""
    from tpustream import StreamExecutionEnvironment
    from tpustream.config import StreamConfig
    from tpustream.jobs.chapter2_max import build

    env = StreamExecutionEnvironment(StreamConfig(
        batch_size=2,
        checkpoint_dir=str(ckdir),
        checkpoint_interval_batches=1,
    ))
    build(env, env.from_collection(LINES)).collect()
    return env


def rewrite_format_version(path, version):
    import numpy as np

    from tpustream.runtime.checkpoint import _META_KEY

    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != _META_KEY}
        meta = json.loads(bytes(z[_META_KEY]).decode())
    meta["version"] = version
    with open(path, "wb") as f:
        np.savez(f, **arrays, **{_META_KEY: np.frombuffer(
            json.dumps(meta).encode(), dtype=np.uint8)})


def main():
    from tpustream.runtime.checkpoint import FORMAT_VERSION

    assert FORMAT_VERSION == 10, (
        f"FORMAT_VERSION moved to {FORMAT_VERSION}: re-point the fixture "
        "names/versions below and update tests/test_schema_audit.py"
    )
    d = tempfile.mkdtemp()
    env = build_env(d)
    env.execute("golden-checkpoint")
    newest = sorted(glob.glob(os.path.join(d, "ckpt-*.npz")))[-1]
    current = os.path.join(HERE, "ckpt-fv10.npz")
    shutil.copy(newest, current)
    for v in (8, 9, 11):
        p = os.path.join(HERE, f"ckpt-fv{v:02d}.npz")
        shutil.copy(current, p)
        rewrite_format_version(p, v)
    for n in sorted(os.listdir(HERE)):
        if n.endswith(".npz"):
            print(n, os.path.getsize(os.path.join(HERE, n)), "bytes")


if __name__ == "__main__":
    main()
