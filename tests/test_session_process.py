"""Session windows with a full-window ProcessWindowFunction.

Combines the reference's session-window surface (chapter3/README.md:
412-428) with its ProcessWindowFunction contract (chapter2/README.md:
177-196): elements buffer per session; on fire the user function sees
key, window context ([min_ts, max_ts + gap)), and every element.
Checked against a record-at-a-time oracle (median per session, like
ComputeCpuMiddle but session-windowed) across batch sizes.
"""

import numpy as np
import pytest

from tpustream import (
    BoundedOutOfOrdernessTimestampExtractor,
    StreamExecutionEnvironment,
    Time,
    TimeCharacteristic,
    Tuple2,
)
from tpustream.api.windows import EventTimeSessionWindows
from tpustream.config import StreamConfig
from tpustream.runtime.sources import ReplaySource

GAP_MS = 10_000
DELAY_MS = 2_000


def parse(value: str) -> Tuple2:
    items = value.split(" ")
    return Tuple2(items[1], int(items[2]))


class TsExtractor(BoundedOutOfOrdernessTimestampExtractor):
    def __init__(self):
        super().__init__(Time.milliseconds(DELAY_MS))

    def extract_timestamp(self, value: str) -> int:
        return int(value.split(" ")[0])


def median_process(key, context, elements, out):
    vals = sorted(e.f1 for e in elements)
    if not vals:
        out.collect(Tuple2(key, 0.0))
    elif len(vals) % 2 == 1:
        out.collect(Tuple2(key, float(vals[len(vals) // 2])))
    else:
        out.collect(
            Tuple2(key, (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]) / 2)
        )


def oracle(records, gap_ms=GAP_MS):
    """Per-key session merge; median of each session's values. Late
    records (solo session closed at arrival watermark) are dropped."""
    wm = -(2**62)
    open_sessions = {}  # key -> list of [min_ts, max_ts, values]
    out = []

    def fire(new_wm):
        for key in sorted(open_sessions):
            keep = []
            for s in sorted(open_sessions[key], key=lambda s: s[0]):
                if s[1] + gap_ms - 1 <= new_wm:
                    vals = sorted(s[2])
                    m = (
                        float(vals[len(vals) // 2])
                        if len(vals) % 2
                        else (vals[len(vals) // 2 - 1] + vals[len(vals) // 2]) / 2
                    )
                    out.append((key, m))
                else:
                    keep.append(s)
            open_sessions[key] = keep

    for ts, key, v in records:
        if ts + gap_ms - 1 <= wm:
            continue
        sess = open_sessions.setdefault(key, [])
        merged = [ts, ts, [v]]
        rest = []
        for s in sess:
            if s[0] - gap_ms < merged[1] and merged[0] - gap_ms < s[1]:
                merged = [
                    min(s[0], merged[0]),
                    max(s[1], merged[1]),
                    s[2] + merged[2],
                ]
            else:
                rest.append(s)
        open_sessions[key] = rest + [merged]
        new_wm = max(wm, ts - DELAY_MS)
        if new_wm > wm:
            fire(new_wm)
            wm = new_wm
    fire(2**62)
    return out


def run_job(lines, batch_size=2):
    env = StreamExecutionEnvironment(
        StreamConfig(batch_size=batch_size, key_capacity=64)
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource(lines))
    handle = (
        text.assign_timestamps_and_watermarks(TsExtractor())
        .map(parse)
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(Time.milliseconds(GAP_MS)))
        .process(median_process)
        .collect()
    )
    env.execute("session-median")
    return [(t.f0, t.f1) for t in handle.items]


def _records():
    rng = np.random.default_rng(5)
    t = 1_000_000
    recs = []
    for burst in range(8):
        key = f"k{burst % 3}"
        for j in range(int(rng.integers(1, 6))):
            recs.append((t + j * 1500, key, int(rng.integers(1, 100))))
        t += int(rng.integers(GAP_MS + 3000, 3 * GAP_MS))
    return recs


@pytest.mark.parametrize("batch_size", [1, 4, 64])
def test_session_process_median_matches_oracle(batch_size):
    recs = _records()
    lines = [f"{ts} {key} {v}" for ts, key, v in recs]
    got = run_job(lines, batch_size=batch_size)
    want = oracle(recs)
    assert sorted(got) == sorted(want)
    assert len(want) >= 8  # the scenario actually produced sessions


def test_adjacent_pane_sessions_do_not_merge():
    """Two same-key sessions whose records are gap..2*gap-1 apart sit in
    ADJACENT panes yet are distinct sessions; when both fire in one step
    the host must split them with the device's link predicate, not pane
    contiguity (regression: they were merged into one window)."""
    env = StreamExecutionEnvironment(
        StreamConfig(batch_size=8, key_capacity=16)
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    lines = [
        "1000000 a 1",   # pane 100
        "1019999 a 2",   # pane 101, 19999 ms later: separate session
        "1100000 b 3",   # watermark passes both ends in the same step
    ]
    text = env.add_source(ReplaySource(lines))
    handle = (
        text.assign_timestamps_and_watermarks(TsExtractor())
        .map(parse)
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(Time.milliseconds(GAP_MS)))
        .process(median_process)
        .collect()
    )
    env.execute("adjacent-sessions")
    assert sorted((t.f0, t.f1) for t in handle.items) == [
        ("a", 1.0),
        ("a", 2.0),
        ("b", 3.0),
    ]


def test_session_process_context_bounds():
    seen = {}

    def probe(key, context, elements, out):
        seen[key] = (context.start, context.end, len(elements))
        out.collect(Tuple2(key, float(len(elements))))

    env = StreamExecutionEnvironment(
        StreamConfig(batch_size=4, key_capacity=16)
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    lines = [
        "1000000 a 1",
        "1003000 a 2",
        "1060000 a 9",  # wm passes first session; also closes at EOS
    ]
    text = env.add_source(ReplaySource(lines))
    handle = (
        text.assign_timestamps_and_watermarks(TsExtractor())
        .map(parse)
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(Time.milliseconds(GAP_MS)))
        .process(probe)
        .collect()
    )
    env.execute("session-ctx")
    # two sessions fired; `seen` keeps the LAST one: [1060000, 1070000)
    assert [(t.f0, t.f1) for t in handle.items] == [("a", 2.0), ("a", 1.0)]
    assert seen["a"] == (1060000, 1060000 + GAP_MS, 1)


def _run_medians(recs, parallelism=1, batch_size=4, lateness_ms=0):
    env = StreamExecutionEnvironment(
        StreamConfig(
            batch_size=batch_size, key_capacity=64, parallelism=parallelism,
        )
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    lines = [f"{ts} {key} {v}" for ts, key, v in recs]
    text = env.add_source(ReplaySource(lines))
    w = (
        text.assign_timestamps_and_watermarks(TsExtractor())
        .map(parse)
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(Time.milliseconds(GAP_MS)))
    )
    if lateness_ms:
        w = w.allowed_lateness(Time.milliseconds(lateness_ms))
    handle = w.process(median_process).collect()
    env.execute("sharded-session-process")
    return sorted((t.f0, t.f1) for t in handle.items)


def test_sharded_session_process_matches_single_chip():
    # round 2's last single-chip-only program shape, now SPMD
    rng = np.random.default_rng(9)
    t = 0
    recs = []
    for _ in range(120):
        t += int(rng.integers(0, 12_000))
        key = str(rng.choice(["a", "b", "c", "d", "e"]))
        recs.append((t, key, int(rng.integers(1, 50))))
    single = _run_medians(recs, parallelism=1, batch_size=8)
    sharded = _run_medians(recs, parallelism=8, batch_size=8)
    assert sharded == single


def test_session_process_lateness_refire():
    L = 30_000
    recs = [
        (1_000_000, "a", 1),
        (1_005_000, "a", 3),
        (1_030_000, "a", 9),   # wm 1028000: [1000000,1005000] fires, med 2
        (1_002_000, "a", 5),   # late, within L: refires merged, med 3
        (1_090_000, "a", 7),
    ]
    got = _run_medians(recs, lateness_ms=L, batch_size=1)
    assert ("a", 2.0) in got          # on-time fire
    assert ("a", 3.0) in got          # late refire with element 5 merged
    # retained sessions refire once per late arrival, not per step
    assert len([x for x in got if x[0] == "a"]) == 4


def test_sharded_session_process_lateness_matches_single_chip():
    rng = np.random.default_rng(13)
    t = 0
    recs = []
    for _ in range(100):
        t += int(rng.integers(0, 9_000))
        key = str(rng.choice(["a", "b", "c"]))
        jitter = int(rng.integers(0, 25_000))
        recs.append((max(0, t - jitter), key, int(rng.integers(1, 50))))
    single = _run_medians(recs, lateness_ms=15_000, batch_size=8)
    sharded = _run_medians(recs, lateness_ms=15_000, parallelism=8, batch_size=8)
    assert sharded == single
