"""Native-parser build failure path (tpustream/native): when neither the
Makefile nor the portable g++ line produces a loadable _fastparse.so,
the job must keep running on the numpy/python parse path, build_error()
must say why, and an obs-enabled run must leave the
``native_parse_unavailable`` flight breadcrumb that explains the
throughput cliff in a postmortem."""

import subprocess

import pytest

from tpustream import StreamExecutionEnvironment, native
from tpustream.config import ObsConfig, StreamConfig
from tpustream.runtime.sources import ReplaySource

LINES = [
    "1563452056 10.8.22.1 cpu0 80.5",
    "1563452050 10.8.22.1 cpu0 78.4",
    "1563452056 10.8.22.2 cpu1 40.0",
    "1563452060 10.8.22.1 cpu0 99.9",
]


@pytest.fixture
def broken_native(monkeypatch, tmp_path):
    """Force the next _load() through a failing build: no cached lib, a
    missing .so path, and a compiler that always errors. monkeypatch
    restores the real module state afterwards."""
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", False)
    monkeypatch.setattr(native, "_build_error", None)
    monkeypatch.setattr(native, "_SO", str(tmp_path / "_fastparse.so"))

    def fail(cmd, **kw):
        raise subprocess.CalledProcessError(
            1, cmd, stderr=b"fatal error: no such toolchain"
        )

    monkeypatch.setattr(native.subprocess, "run", fail)
    return native


def test_build_failure_surfaces_error_and_stays_unavailable(broken_native):
    assert not broken_native.available()
    err = broken_native.build_error()
    assert err is not None
    # both attempts are named with their compiler tails
    assert "make" in err and "g++" in err and "no such toolchain" in err
    # the failure is cached — no rebuild storm on every parse call
    assert not broken_native.available()


def test_numpy_fallback_parses_and_leaves_flight_breadcrumb(broken_native):
    from tpustream.jobs.chapter2_max import build

    env = StreamExecutionEnvironment(
        StreamConfig(batch_size=2, obs=ObsConfig(enabled=True))
    )
    handle = build(env, env.add_source(ReplaySource(LINES))).collect()
    env.execute("native-fallback-test")
    # numpy path produced real output
    assert len(handle.items) == len(LINES)
    events = env.metrics.job_obs.flight.events()
    crumbs = [e for e in events if e["kind"] == "native_parse_unavailable"]
    assert len(crumbs) == 1, [e["kind"] for e in events]
    assert "no such toolchain" in crumbs[0]["error"]


def test_dlopen_failure_triggers_one_rebuild(monkeypatch, tmp_path):
    """A checked-in .so from another toolchain dlopen-fails even though
    it is newer than the source: _load() must rebuild once against this
    toolchain instead of silently dropping to numpy."""
    so = tmp_path / "_fastparse.so"
    so.write_bytes(b"\x7fELF not really a library")
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", False)
    monkeypatch.setattr(native, "_build_error", None)
    monkeypatch.setattr(native, "_SO", str(so))
    calls = []

    def fake_build():
        calls.append(1)
        native._build_error = "rebuild failed too"
        return False

    monkeypatch.setattr(native, "_build", fake_build)
    assert not native.available()
    assert len(calls) == 1, "dlopen failure must attempt exactly one rebuild"
    err = native.build_error()
    assert "dlopen" in err and "rebuild failed too" in err
