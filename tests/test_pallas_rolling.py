"""Pallas sequential in-VMEM keyed reduce (the VERDICT r2 #5 experiment).

Correctness is pinned here in interpreter mode against a record-at-a-
time numpy oracle; the performance verdict (whether it replaces the
sort+scan rolling fast path) is measured on the real chip by
``python -m tpustream.ops.pallas_rolling`` and recorded in
docs/architecture.md.
"""

import numpy as np
import pytest

jnp = pytest.importorskip("jax.numpy")

from tpustream.ops import pallas_rolling as P


# "min" is dropped from the sweep: the kernel differs from "max" only
# in the combiner intrinsic, and each interpret-mode run costs ~14 s on
# the 1-core gate host (VERDICT r4 next #7)
@pytest.mark.parametrize("op", ["max", "sum"])
def test_seq_rolling_reduce_matches_oracle(op):
    if not P._supported():
        pytest.skip("pallas unavailable")
    rng = np.random.default_rng(7)
    # 3 row-blocks x 2 key-blocks: every kernel code path (block sweep,
    # in-block sequential RMW, cross-block carry) at interpret-mode
    # cost the suite budget can afford
    B, K = 384, 256
    keys = rng.integers(0, K, B, dtype=np.int32).reshape(B // 128, 128)
    vals = (rng.random(B, dtype=np.float32) * 100).reshape(B // 128, 128)
    ident = {"max": -np.inf, "min": np.inf, "sum": 0.0}[op]
    plane = np.full((K // 128, 128), ident, dtype=np.float32)
    want_plane, want_emis = P.oracle(plane, keys, vals, op)
    got_plane, got_emis = P.seq_rolling_reduce(
        jnp.asarray(plane), jnp.asarray(keys), jnp.asarray(vals),
        op=op, interpret=True,
    )
    assert np.allclose(np.asarray(got_plane), want_plane)
    assert np.allclose(np.asarray(got_emis), want_emis)


def test_seq_rolling_reduce_repeated_keys_sequential_semantics():
    # many hits on one key in one batch: emissions must be the exact
    # running prefix in arrival order (the Flink rolling contract)
    if not P._supported():
        pytest.skip("pallas unavailable")
    B, K = 256, 128
    keys = np.zeros((B // 128, 128), dtype=np.int32)
    vals = np.arange(B, dtype=np.float32).reshape(B // 128, 128)
    plane = np.full((K // 128, 128), -np.inf, dtype=np.float32)
    _, emis = P.seq_rolling_reduce(
        jnp.asarray(plane), jnp.asarray(keys), jnp.asarray(vals),
        op="max", interpret=True,
    )
    # ascending values on one key: running max == the value itself
    assert np.allclose(np.asarray(emis).reshape(-1), np.arange(B))
