"""Raw-bytes ingest lane: ReplayBytesSource -> native parse -> device.

The raw lane must be observationally identical to the per-line path for
the same batch boundaries — stateless chains, event-time windows with
watermark progression, and checkpoint resume line-skipping included.
(Reference surface: the socket byte stream of chapter1/README.md:65-84;
the lane exists so the host can ingest at device rate on one core.)
"""

import numpy as np
import pytest

from tpustream import StreamExecutionEnvironment
from tpustream.config import StreamConfig
from tpustream.jobs.chapter1_threshold import build as build_ch1
from tpustream.jobs.chapter3_bandwidth_eventtime import build as build_ch3
from tpustream.runtime.sources import ReplayBytesSource, ReplaySource


def _to_buffers(lines, per_buf):
    return [
        ("\n".join(lines[i : i + per_buf]).encode(), len(lines[i : i + per_buf]))
        for i in range(0, len(lines), per_buf)
    ]


def _native_available():
    from tpustream import native as native_mod

    return native_mod.available()


pytestmark = pytest.mark.skipif(
    not _native_available(), reason="native parser not built"
)


def _run(job_build, source, name, event_time=False, **cfg):
    from tpustream import TimeCharacteristic

    env = StreamExecutionEnvironment(StreamConfig(**cfg))
    if event_time:
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(source)
    handle = job_build(env, text).collect()
    env.execute(name)
    return handle.items, env.metrics


def test_ch1_raw_equals_line_path():
    lines = [
        f"1563452051 10.8.22.{i%4} cpu{i%3} {50 + (i % 60)}.5" for i in range(100)
    ]
    want, _ = _run(build_ch1, ReplaySource(lines), "ch1", batch_size=16)
    got, m = _run(
        build_ch1,
        ReplayBytesSource(_to_buffers(lines, 16)),
        "ch1-raw",
        batch_size=16,
    )
    assert got == want
    assert m.records_in == 100


def test_ch3_eventtime_raw_equals_line_path():
    # watermark progression across buffers must match the line path:
    # same buffer boundaries -> same per-step watermark -> same fires
    lines = []
    for m in range(12):
        for s in (3, 17, 41):
            lines.append(f"2019-08-28T10:{m:02d}:{s:02d} www.163.com {700+m}")
            lines.append(f"2019-08-28T10:{m:02d}:{s:02d} www.btime.com {80000+m}")
    want, _ = _run(
        build_ch3, ReplaySource(lines), "ch3", event_time=True, batch_size=8
    )
    got, _ = _run(
        build_ch3,
        ReplayBytesSource(_to_buffers(lines, 8)),
        "ch3-raw",
        event_time=True,
        batch_size=8,
    )
    assert want  # the job actually fired windows
    assert got == want


def test_raw_fallback_decodes_for_non_symbolic_jobs(tmp_path):
    # a per-record Python map can't use the native lane; the executor
    # must decode the buffer and produce identical output anyway
    lines = ["1 a x 5", "2 b y 7"]

    def pymap(line):
        parts = line.split(" ")
        return (parts[1], float(parts[3]))

    def run(src):
        env = StreamExecutionEnvironment(StreamConfig(batch_size=4))
        text = env.add_source(src)
        handle = text.map(pymap).collect()
        env.execute("py")
        return handle.items

    assert run(ReplayBytesSource(_to_buffers(lines, 2))) == run(
        ReplaySource(lines)
    )


def test_socket_raw_mode_equals_line_mode():
    """SocketTextSource(raw=True) must produce the same job output as
    line mode for the same byte stream (chapter1 threshold job)."""
    import socket
    import threading

    lines = [
        f"1563452051 10.8.22.{i%4} cpu{i%3} {50 + (i % 60)}.5"
        for i in range(64)
    ]
    payload = ("\n".join(lines) + "\n").encode()

    def serve(srv):
        conn, _ = srv.accept()
        # two sends with a gap: exercises block re-assembly mid-stream
        conn.sendall(payload[: len(payload) // 2])
        import time as _t

        _t.sleep(0.05)
        conn.sendall(payload[len(payload) // 2 :])
        conn.close()
        srv.close()

    def run(raw):
        # bind FIRST, then hand the listening socket to the server
        # thread — no rebind race, and the source always finds a listener
        srv = socket.create_server(("127.0.0.1", 0))
        port = srv.getsockname()[1]
        t = threading.Thread(target=serve, args=(srv,), daemon=True)
        t.start()
        env = StreamExecutionEnvironment(
            StreamConfig(batch_size=16, max_batch_delay_ms=100.0)
        )
        text = env.socket_text_stream("127.0.0.1", port, raw=raw)
        handle = build_ch1(env, text).collect()
        env.execute("ch1-socket")
        t.join(timeout=10)
        return handle.items

    want = run(raw=False)
    got = run(raw=True)
    assert want  # alerts actually flowed
    assert got == want


def test_raw_resume_skips_consumed_lines(tmp_path):
    lines = [
        f"1563452051 10.8.22.{i%2} cpu0 {91 + (i % 5)}.5" for i in range(40)
    ]
    ckdir = str(tmp_path / "ck")

    env = StreamExecutionEnvironment(
        StreamConfig(
            batch_size=8,
            checkpoint_dir=ckdir,
            # 5 data batches + 1 final empty batch: the ONLY checkpoint
            # lands after batch 4 (32 lines) — neither the full stream
            # nor a multiple of the resume chunking below
            checkpoint_interval_batches=4,
        )
    )
    text = env.add_source(ReplayBytesSource(_to_buffers(lines, 8)))
    h1 = build_ch1(env, text).collect()
    env.execute("ch1-ck")
    full = h1.items

    # resume with DIFFERENT buffer chunking (12/buffer vs the 8/buffer
    # checkpoint run): skipping 32 lines lands 8 lines INTO the third
    # buffer, exercising the newline-scanning partial raw trim
    env2 = StreamExecutionEnvironment(StreamConfig(batch_size=8))
    env2.restore_from_checkpoint(ckdir)
    text2 = env2.add_source(ReplayBytesSource(_to_buffers(lines, 12)))
    h2 = build_ch1(env2, text2).collect()
    env2.execute("ch1-resume")
    # 8 lines remain past the checkpoint; all alert (usage > 90)
    assert 0 < len(h2.items) < len(full)
    assert h2.items == full[len(full) - len(h2.items):]
