"""CEP pattern matching (tpustream/cep/ + runtime/cep_program.py):
device output vs the pure-Python oracle NFA across the edge cases the
vectorized advance must get right — strict/relaxed contiguity,
overlapping ``times()`` partials, ``within()`` timeouts exactly at the
watermark boundary, late events under allowed lateness — plus the
single-chip vs p=8 mesh parity of the chapter-4 job."""

import numpy as np
import pytest

from tpustream import (
    CEP,
    BoundedOutOfOrdernessTimestampExtractor,
    OutputTag,
    Pattern,
    PatternSelectFunction,
    StreamExecutionEnvironment,
    Time,
    TimeCharacteristic,
    Tuple2,
    Tuple3,
)
from tpustream.cep import compile_pattern, run_oracle
from tpustream.config import StreamConfig
from tpustream.javacompat import Long
from tpustream.runtime.sources import ReplaySource

# ---------------------------------------------------------------------------
# line format: "<epoch-sec> <channel> <value>" (chapter-2 style)
# ---------------------------------------------------------------------------


class SecondExtractor(BoundedOutOfOrdernessTimestampExtractor):
    def __init__(self, delay=None):
        super().__init__(delay or Time.seconds(0))

    def extract_timestamp(self, element):
        return Long.parseLong(element.split(" ")[0]) * 1000


def parse(s):
    items = s.split(" ")
    return Tuple3(
        Long.parseLong(items[0]), items[1], Long.parseLong(items[2])
    )


def lines_of(events):
    """events: (sec, channel, value) triples."""
    return [f"{t} {ch} {v}" for t, ch, v in events]


def run_cep(
    events, pattern, select_fn=None, batch_size=2, parallelism=1,
    delay=None, allowed_lateness=None, late_tag=None, timeout_tag=None,
    **cfg_over,
):
    env = StreamExecutionEnvironment(
        StreamConfig(batch_size=batch_size, parallelism=parallelism,
                     **cfg_over)
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource(lines_of(events)))
    keyed = (
        text.assign_timestamps_and_watermarks(SecondExtractor(delay))
        .map(parse)
        .key_by(1)
    )
    ps = CEP.pattern(keyed, pattern)
    if allowed_lateness is not None:
        ps = ps.allowed_lateness(allowed_lateness)
    if late_tag is not None:
        ps = ps.side_output_late_data(late_tag)
    result = ps.select(select_fn, timeout_tag=timeout_tag)
    h = result.collect()
    ht = result.get_side_output(timeout_tag).collect() if timeout_tag else None
    hl = result.get_side_output(late_tag).collect() if late_tag else None
    env.execute("cep-test")
    return (
        h.items,
        ht.items if ht else [],
        hl.items if hl else [],
        env.metrics.summary(),
    )


def oracle_for(events, pattern, batch_size=2, delay_ms=0,
               allowed_lateness_ms=0):
    recs = [((t, ch, v), t * 1000) for t, ch, v in events]
    batches = [
        recs[i:i + batch_size] for i in range(0, len(recs), batch_size)
    ]
    return run_oracle(
        pattern, batches, delay_ms=delay_ms,
        allowed_lateness_ms=allowed_lateness_ms,
    )


def flat_matches(oracle_matches):
    """Oracle match (list of event tuples) -> the device's flat record."""
    return [tuple(v for ev in m for v in ev) for m in oracle_matches]


def timeout_rows(oracle_timeouts, R):
    """Oracle (n, start, events) -> the device timeout record with its
    deterministic padding (None for strings, 0 for numbers)."""
    rows = []
    for n, start, evs in oracle_timeouts:
        row = [n, start]
        for e in range(R):
            row.extend(evs[e] if e < len(evs) else (0, None, 0))
        rows.append(tuple(row))
    return rows


# ---------------------------------------------------------------------------
# builder / compiler validation
# ---------------------------------------------------------------------------
def test_pattern_builder_validation():
    with pytest.raises(ValueError, match="must be >= 1"):
        Pattern.begin("a").times(0)
    with pytest.raises(ValueError, match="positive"):
        Pattern.begin("a").within(0)
    with pytest.raises(ValueError, match="empty pattern"):
        compile_pattern(Pattern())
    with pytest.raises(ValueError, match="duplicate stage names"):
        compile_pattern(Pattern.begin("a").followed_by("a"))
    with pytest.raises(ValueError, match="plain filter"):
        compile_pattern(Pattern.begin("only"))
    with pytest.raises(ValueError, match="where"):
        Pattern().where(lambda r: True)


def test_compile_expands_times_and_strictness():
    p = (
        Pattern.begin("a").next("b").times(3).consecutive()
        .followed_by("c").within(Time.seconds(5))
    )
    cp = compile_pattern(p)
    assert cp.length == 5
    assert list(cp.stage_of) == [0, 1, 1, 1, 2]
    # begin relaxed; b strict entry + consecutive reps; c relaxed
    assert list(cp.strict) == [False, True, True, True, False]
    assert cp.within_ms == 5000
    t = cp.transition_table()
    assert t.shape == (6, 2)
    assert list(t[:, 1]) == [1, 2, 3, 4, 5, 5]    # fired: advance
    # missed: start and relaxed-edge states survive, strict-edge die
    assert list(t[:, 0]) == [0, -1, -1, -1, 4, 5]


# ---------------------------------------------------------------------------
# device vs oracle
# ---------------------------------------------------------------------------
REL2 = lambda: (  # noqa: E731 — rebuilt per test (builder mutates)
    Pattern.begin("a").where(lambda r: r.f2 > 10)
    .followed_by("b").where(lambda r: r.f2 > 10)
)


def test_relaxed_skips_nonmatching_events():
    events = [(0, "k", 20), (1, "k", 5), (2, "k", 30), (3, "k", 40)]
    out, _, _, summ = run_cep(events, REL2())
    m, _, _ = oracle_for(events, REL2())
    assert out == flat_matches(m)
    assert [r[5] for r in out] == [30, 40]  # overlapping {20,30}, {30,40}
    assert summ["cep_matches"] == 2 and summ["cep_timeouts"] == 0


def test_strict_next_kills_broken_runs():
    strict = lambda: (  # noqa: E731
        Pattern.begin("a").where(lambda r: r.f2 > 10)
        .next("b").where(lambda r: r.f2 > 10)
    )
    events = [(0, "k", 20), (1, "k", 5), (2, "k", 30), (3, "k", 40)]
    out, _, _, _ = run_cep(events, strict())
    m, _, _ = oracle_for(events, strict())
    assert out == flat_matches(m)
    # the 5 breaks the 20- run; only the contiguous {30,40} matches
    assert len(out) == 1 and out[0][2] == 30 and out[0][5] == 40


def test_times_overlapping_partials_match_oracle():
    p = lambda: Pattern.begin("a").where(lambda r: r.f2 > 10).times(3)  # noqa: E731
    events = [
        (0, "k", 20), (1, "k", 21), (2, "k", 5), (3, "k", 22),
        (4, "k", 23), (5, "k", 24),
    ]
    out, _, _, _ = run_cep(events, p())
    m, _, _ = oracle_for(events, p())
    assert out == flat_matches(m)
    # relaxed times: {20,21,22}, {21,22,23}, {22,23,24}
    assert [(r[2], r[5], r[8]) for r in out] == [
        (20, 21, 22), (21, 22, 23), (22, 23, 24)
    ]


def test_within_timeout_exactly_at_watermark_boundary():
    p = lambda: (  # noqa: E731
        Pattern.begin("a").where(lambda r: r.f2 > 10)
        .followed_by("b").where(lambda r: r.f2 > 10)
        .within(Time.seconds(10))
    )
    tag = OutputTag("to")
    # partial starts at t=0; the t=10 event is EXACTLY at the within
    # bound: ts - start == within must NOT extend (strictly-less
    # semantics), and the watermark reaching start + within exactly
    # (wm >= start + within) fires the timeout in the same step. The
    # t=10 event also cannot START a partial: the sweep runs after the
    # batch's events, so the expired partial still holds the register
    events = [(0, "k", 20), (10, "k", 30)]
    out, tmo, _, summ = run_cep(events, p(), batch_size=1, timeout_tag=tag)
    m, t, _ = oracle_for(events, p(), batch_size=1)
    assert out == flat_matches(m) == []
    assert tmo == timeout_rows(t, R=1)
    assert [(r[0], r[1]) for r in tmo] == [(1, 0)]
    assert summ["cep_timeouts"] == 1
    # one second inside the bound: the same shape completes instead
    events_in = [(0, "k", 20), (9, "k", 30)]
    out2, tmo2, _, _ = run_cep(events_in, p(), batch_size=1, timeout_tag=tag)
    m2, t2, _ = oracle_for(events_in, p(), batch_size=1)
    assert out2 == flat_matches(m2)
    assert len(out2) == 1 and tmo2 == timeout_rows(t2, R=1)


def test_late_events_under_allowed_lateness():
    p = lambda: (  # noqa: E731
        Pattern.begin("a").where(lambda r: r.f2 > 10)
        .followed_by("b").where(lambda r: r.f2 > 10)
    )
    late_tag = OutputTag("late")
    # watermark rides to 100s on key k2; then a k1 event 3s behind the
    # watermark (inside allowed lateness 5s — still matches) and one
    # 50s behind (diverted to the late side output)
    events = [
        (100, "k1", 20), (100, "k2", 1),
        (97, "k1", 30),      # behind wm, within lateness: completes
        (50, "k1", 99),      # beyond lateness: late stream
    ]
    al = Time.seconds(5)
    out, _, late, summ = run_cep(
        events, p(), batch_size=2, allowed_lateness=al, late_tag=late_tag
    )
    m, _, l = oracle_for(events, p(), batch_size=2, allowed_lateness_ms=5000)
    assert out == flat_matches(m)
    assert len(out) == 1 and out[0][5] == 30
    assert [tuple(r) for r in late] == l == [(50, "k1", 99)]
    assert summ["late_dropped"] == 0  # routed, not dropped


def test_select_function_dict_and_java_aliases():
    class SumSelect(PatternSelectFunction):
        def select(self, match):
            a0, a1 = match["spike"]
            end = match["probe"][0]
            return Tuple2(a0.f1, a0.f2 + a1.f2 + end.f2)

    # camelCase surface: followedBy + a SAM select class
    p = (
        Pattern.begin("spike").where(lambda r: r.f2 > 10).times(2)
        .followedBy("probe").where(lambda r: r.f2 < 0)
    )
    events = [(0, "k", 20), (1, "k", 22), (2, "k", -1)]
    out, _, _, _ = run_cep(events, p, select_fn=SumSelect())
    assert [repr(t) for t in out] == ["(k,41)"]


def test_multiple_keys_independent_state():
    p = lambda: (  # noqa: E731
        Pattern.begin("a").where(lambda r: r.f2 > 10)
        .next("b").where(lambda r: r.f2 > 10)
    )
    # interleaved keys: strict contiguity is PER KEY (k1's run is not
    # broken by k2's records in between)
    events = [
        (0, "k1", 20), (1, "k2", 5), (2, "k1", 30), (3, "k2", 40),
        (4, "k2", 50),
    ]
    out, _, _, _ = run_cep(events, p(), batch_size=2)
    m, _, _ = oracle_for(events, p(), batch_size=2)
    assert sorted(out) == sorted(flat_matches(m))
    assert len(out) == 2  # k1: {20,30}; k2: {40,50}


def test_single_batch_multi_event_per_key_rounds():
    # every event in ONE batch: the while_loop's per-rank rounds must
    # replay the arrival order within the batch
    p = lambda: Pattern.begin("a").where(lambda r: r.f2 > 10).times(3)  # noqa: E731
    events = [(i, "k", 20 + i) for i in range(6)]
    out, _, _, _ = run_cep(events, p(), batch_size=8)
    m, _, _ = oracle_for(events, p(), batch_size=8)
    assert out == flat_matches(m)
    assert len(out) == 4


def test_chapter4_job_matches_oracle_and_p8_parity():
    from tpustream.jobs.chapter4_cep_alert import build, make_pattern
    from tpustream.utils.timeutil import iso_local_to_epoch_sec

    LINES = [
        "2019-08-28T10:00:00 www.163.com 6000",
        "2019-08-28T10:00:10 www.163.com 7000",
        "2019-08-28T10:00:20 www.163.com 8000",
        "2019-08-28T10:00:30 www.sina.com 6100",
        "2019-08-28T10:00:40 www.sina.com 7100",
        "2019-08-28T10:01:00 www.163.com 9000",
        "2019-08-28T10:00:50 www.sina.com 8100",  # out of order, in bound
        "2019-08-28T10:05:00 www.qq.com 50",      # advances the watermark
    ]

    def run(p):
        env = StreamExecutionEnvironment(
            StreamConfig(batch_size=8, parallelism=p)
        )
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        text = env.add_source(ReplaySource(LINES))
        tag = OutputTag("breach-timeout")
        alerts = build(env, text, timeout_tag=tag)
        h = alerts.collect()
        ht = alerts.get_side_output(tag).collect()
        env.execute(f"cep-chapter4-p{p}")
        return [repr(t) for t in h.items], [repr(t) for t in ht.items]

    # oracle over the same batch boundaries (batch_size=8: one batch)
    recs = []
    for line in LINES:
        iso, ch, flow = line.split(" ")
        sec = iso_local_to_epoch_sec(iso)
        recs.append(((sec, ch, int(flow)), sec * 1000))
    m, t, _ = run_oracle(make_pattern(), [recs], delay_ms=5000)
    want_alerts = [
        f"({b0[1]},{b0[2] + b1[2] + b2[2]},{b0[0]},{b2[0]})"
        for b0, b1, b2 in m
    ]

    a1, t1 = run(1)
    assert a1 == want_alerts
    assert sorted(t1) == sorted(
        repr(r) for r in timeout_rows(t, R=2)
    )
    a8, t8 = run(8)
    assert sorted(a8) == sorted(a1)
    assert sorted(t8) == sorted(t1)


def test_processing_time_pattern_no_assigner_needed():
    # processing time: no timestamp assigner, watermark = max_proc - 1
    env = StreamExecutionEnvironment(StreamConfig(batch_size=2))
    env.set_stream_time_characteristic(TimeCharacteristic.ProcessingTime)
    text = env.add_source(
        ReplaySource(lines_of([(0, "k", 20), (1, "k", 30)]))
    )
    keyed = text.map(parse).key_by(1)
    p = (
        Pattern.begin("a").where(lambda r: r.f2 > 10)
        .followed_by("b").where(lambda r: r.f2 > 10)
    )
    h = CEP.pattern(keyed, p).select(
        lambda match: Tuple2(match["a"][0].f1, match["b"][0].f2)
    ).collect()
    env.execute("cep-proctime")
    assert [repr(t) for t in h.items] == ["(k,30)"]


def test_event_time_pattern_requires_assigner():
    env = StreamExecutionEnvironment(StreamConfig())
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource(lines_of([(0, "k", 20)])))
    keyed = text.map(parse).key_by(1)
    CEP.pattern(keyed, REL2()).select().collect()
    with pytest.raises(RuntimeError, match="event-time"):
        env.execute("cep-no-assigner")


def test_cep_requires_keyed_stream():
    env = StreamExecutionEnvironment(StreamConfig())
    text = env.add_source(ReplaySource(["x"]))
    with pytest.raises(TypeError, match="keyed stream"):
        CEP.pattern(text, REL2())
