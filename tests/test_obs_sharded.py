"""Observability under the sharded mesh path (8 virtual CPU devices):
per-shard label sets stay distinct, cross-registry merge (the
multi-host aggregation primitive) is loss-free, health rules evaluate
over merged series, and an obs-enabled parallelism=8 chapter-3 job
reports the same record counts as single-chip plus the sharded-only
gauges and end-to-end latency markers."""

import jax
import pytest

from tpustream import StreamExecutionEnvironment, Time, TimeCharacteristic
from tpustream.config import ObsConfig, StreamConfig
from tpustream.jobs.chapter3_bandwidth_eventtime import build as build_et
from tpustream.obs import (
    AlertRule,
    HealthEngine,
    JobObs,
    MetricsRegistry,
)
from tpustream.runtime.sources import ReplaySource

pytestmark = pytest.mark.skipif(
    jax.device_count() < 8, reason="needs the 8-virtual-device CPU mesh"
)


# ---------------------------------------------------------------------------
# per-shard labeling + registry merge (no device work)
# ---------------------------------------------------------------------------


def test_per_shard_operator_labels_distinct():
    job = JobObs(ObsConfig(enabled=True), job_name="j")
    op = job.operator("window")
    shards = [op.shard(i) for i in range(8)]
    for i, sh in enumerate(shards):
        sh.records_in.inc(10 + i)
    series = {
        s.labels["shard"]: s
        for s in job.registry.series()
        if s.name == "operator_records_in" and "shard" in s.labels
    }
    assert sorted(series) == [str(i) for i in range(8)]  # all distinct
    for i in range(8):
        assert series[str(i)].value == 10 + i  # no cross-shard bleed
        assert series[str(i)].labels["operator"] == "window"


def test_registry_merge_across_shards_lossless_and_health_over_merged():
    """The multi-host shape: each shard keeps its own registry; the
    coordinator merges them and evaluates health over the union."""
    regs = []
    for i in range(8):
        r = MetricsRegistry()
        g = r.group(job="j", operator="window", shard=str(i))
        g.counter("operator_records_in").inc(100 + i)
        g.histogram("operator_e2e_latency_ms").observe_many(
            [float(i + 1), float(i + 2)]
        )
        regs.append(r)

    merged = MetricsRegistry()
    for r in regs:
        merged.merge(r)

    series = list(merged.series())
    counters = [s for s in series if s.name == "operator_records_in"]
    assert len(counters) == 8  # one per shard, none collapsed
    assert sum(s.value for s in counters) == sum(100 + i for i in range(8))
    hists = [s for s in series if s.name == "operator_e2e_latency_ms"]
    assert sum(h.count for h in hists) == 16  # exact under merge
    assert sum(h.sum for h in hists) == sum(
        (i + 1) + (i + 2) for i in range(8)
    )

    # a single rule set sees every shard's series; agg=max picks the
    # worst shard, the label filter pins one shard
    snap = merged.snapshot()["series"]
    engine = HealthEngine([
        AlertRule(name="hot_shard", metric="operator_records_in",
                  op=">", value=106, agg="max", severity="crit"),
        AlertRule(name="shard0", metric="operator_records_in",
                  op=">", value=100, labels={"shard": "0"},
                  severity="warn"),
    ])
    state = engine.evaluate(snap, now_s=1.0)
    by_name = {r["rule"]: r for r in state["rules"]}
    assert by_name["hot_shard"]["level"] == "crit"   # shard 7: 107 > 106
    assert by_name["hot_shard"]["value"] == 107
    assert by_name["shard0"]["level"] == "ok"        # shard 0: 100, not > 100


# ---------------------------------------------------------------------------
# e2e: obs-enabled sharded job vs single-chip
# ---------------------------------------------------------------------------

LINES = [
    f"2019-08-28T10:{i // 20:02d}:{(i * 7) % 60:02d} "
    f"www.ch{i % 16}.com {100 + (i % 13) * 10}"
    for i in range(200)
]


def _run(parallelism):
    cfg = StreamConfig(
        parallelism=parallelism,
        batch_size=40,
        key_capacity=64,
        print_parallelism=1,
        obs=ObsConfig(
            enabled=True,
            latency_marker_interval_ms=1e-6,
            health_rules=(
                AlertRule(name="lag_crit", metric="watermark_lag_ms",
                          op=">", value=30_000, severity="crit"),
            ),
        ),
    )
    env = StreamExecutionEnvironment(cfg)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    h = build_et(env, env.add_source(ReplaySource(LINES))).collect()
    env.execute("obs-sharded")
    return env.metrics, sorted((t.f0, round(t.f1, 12)) for t in h.items)


def test_hbm_state_bytes_shard_split_sums_to_single_chip_total():
    """State-memory accounting is consistent across the mesh: the state
    pytree is global, so the p=8 total equals the single-chip total
    byte-for-byte, the per-shard attribution series sum back to it
    exactly, and the shard label set / exchange staging gauge exist
    only on the mesh."""

    def _hbm(metrics):
        total, shards, exchange = None, {}, None
        for s in metrics.obs_snapshot()["metrics"]["series"]:
            if s["name"] == "operator_hbm_state_bytes":
                if "shard" in s["labels"]:
                    shards[s["labels"]["shard"]] = s["value"]
                else:
                    total = s["value"]
            elif s["name"] == "operator_exchange_buffer_bytes":
                exchange = s["value"]
        return total, shards, exchange

    m1, out1 = _run(parallelism=1)
    m8, out8 = _run(parallelism=8)
    assert out1 == out8

    tot1, shards1, ex1 = _hbm(m1)
    tot8, shards8, ex8 = _hbm(m8)
    assert tot1 > 0
    assert shards1 == {} and ex1 is None  # single chip: no mesh series
    assert tot8 == tot1
    assert sorted(shards8) == [str(i) for i in range(8)]
    assert sum(shards8.values()) == tot8
    assert ex8 > 0


def test_sharded_job_obs_matches_single_chip():
    m1, out1 = _run(parallelism=1)
    m8, out8 = _run(parallelism=8)
    assert out1 == out8  # obs never changes results

    s1 = {(s["name"], s["labels"].get("operator")): s
          for s in m1.obs_snapshot()["metrics"]["series"]}
    s8 = {(s["name"], s["labels"].get("operator")): s
          for s in m8.obs_snapshot()["metrics"]["series"]}

    # same record accounting either way
    for key in (("records_in", None), ("operator_records_in", "window")):
        assert s8[key]["value"] == s1[key]["value"] == len(LINES)

    # sharded-only surface: the exchange-capacity gauge
    assert ("operator_exchange_capacity_rows", "window") in s8
    assert s8[("operator_exchange_capacity_rows", "window")]["value"] > 0
    assert ("operator_exchange_capacity_rows", "window") not in s1

    # markers survive the sharded path end to end, none lost
    for s in (s1, s8):
        emitted = s[("latency_markers_emitted", None)]["value"]
        assert emitted >= 4  # 200 lines / 40-row batches = 5 polls
        h = s[("operator_sink0_e2e_latency_ms", "window")]
        assert h["value"]["count"] == emitted
        assert h["value"]["p50"] > 0

    # the health engine saw the merged/sharded series identically
    for m in (m1, m8):
        health = m.obs_snapshot()["health"]
        assert health["rules"][0]["rule"] == "lag_crit"
        assert health["rules"][0]["level"] == "crit"  # 60 s bounded delay


# ---------------------------------------------------------------------------
# time-series merge across p=8 shard registries (satellite: windowed
# queries over the merged history match a single-chip oracle)
# ---------------------------------------------------------------------------


def _pin(reg, clk):
    """Put a registry on the shared fake timeline (wall == perf epoch),
    so cross-registry history merges line up deterministically."""
    reg.now = lambda: clk[0]
    reg._epoch_wall = 0.0
    reg._epoch_perf = 0.0
    return reg


def test_timeseries_merge_p8_matches_single_chip_oracle():
    """Eight shard registries on one shared timeline, each counting at
    its own rate and observing its own latencies, merged into one
    coordinator registry: the merged ``rate()`` equals the sum of the
    per-shard rates and the merged ``quantile()`` equals a single
    registry that saw every observation — lossless, not approximate."""
    clk = [0.0]
    shards = []
    oracle = _pin(MetricsRegistry(), clk)
    oc = oracle.group(job="j").counter("records_in")
    oh = oracle.group(job="j").histogram("e2e_latency_ms")
    for i in range(8):
        r = _pin(MetricsRegistry(), clk)
        shards.append(r)
    # mint the shard instruments at t=0 so every zero-anchor shares the
    # timeline origin
    scs = [r.group(job="j").counter("records_in") for r in shards]
    shs = [r.group(job="j").histogram("e2e_latency_ms") for r in shards]
    for t in range(1, 11):
        clk[0] = float(t)
        for i in range(8):
            scs[i].inc(i + 1)            # shard i ingests (i+1) rows/s
            oc.inc(i + 1)
            lat = float(10 * (i + 1) + t % 3)
            shs[i].observe(lat)
            oh.observe(lat)

    merged = _pin(MetricsRegistry(), clk)
    for r in shards:
        merged.merge(r)

    mc = merged.find("records_in", {"job": "j"})
    mh = merged.find("e2e_latency_ms", {"job": "j"})
    # lossless totals
    assert mc.value == oc.value == 10 * sum(range(1, 9))
    assert mh.count == oh.count == 80
    assert mh.sum == pytest.approx(oh.sum)
    # windowed rate over the merged cumulative history == sum of the
    # per-shard windowed rates == the oracle's rate
    per_shard = sum(c.history.rate(9.0) for c in scs)
    assert mc.history.rate(9.0) == pytest.approx(per_shard)
    assert mc.history.rate(9.0) == pytest.approx(oc.history.rate(9.0))
    assert mc.history.rate(9.0) == pytest.approx(float(sum(range(1, 9))))
    # windowed quantiles over the merged sample history == single-chip
    for q in (0.1, 0.5, 0.9, 0.99):
        assert mh.history.quantile(q, 9.0) == pytest.approx(
            oh.history.quantile(q, 9.0)
        )
    assert mh.history.mean(9.0) == pytest.approx(oh.history.mean(9.0))


def test_tenant_series_merge_p8_matches_single_chip_oracle():
    """Per-tenant latency series across 8 shard registries, merged into
    one coordinator registry: each tenant's merged histogram is lossless
    (windowed quantiles match an oracle registry that saw every one of
    that tenant's observations), and tenant SLO rules evaluated over the
    MERGED series attribute the breach to the noisy tenant only."""
    clk = [0.0]
    tenants = ["acme", "globex", "initech"]
    oracle = _pin(MetricsRegistry(), clk)
    ohs = {
        t: oracle.group(job="j", tenant=t).histogram("tenant_e2e_latency_ms")
        for t in tenants
    }
    shards = [_pin(MetricsRegistry(), clk) for _ in range(8)]
    shs = {
        (i, t): shards[i].group(job="j", tenant=t).histogram(
            "tenant_e2e_latency_ms"
        )
        for i in range(8)
        for t in tenants
    }
    for tick in range(1, 11):
        clk[0] = float(tick)
        for i in range(8):
            for tenant in tenants:
                # acme is the noisy tenant: 10x everyone's latency
                scale = 10.0 if tenant == "acme" else 1.0
                lat = scale * (i + 1) + tick % 3
                shs[(i, tenant)].observe(lat)
                ohs[tenant].observe(lat)

    merged = _pin(MetricsRegistry(), clk)
    for r in shards:
        merged.merge(r)

    for tenant in tenants:
        mh = merged.find(
            "tenant_e2e_latency_ms", {"job": "j", "tenant": tenant}
        )
        oh = ohs[tenant]
        assert mh.count == oh.count == 80
        assert mh.sum == pytest.approx(oh.sum)
        for q in (0.5, 0.9, 0.99):
            assert mh.percentile(q) == pytest.approx(oh.percentile(q))
            assert mh.history.quantile(q, 9.0) == pytest.approx(
                oh.history.quantile(q, 9.0)
            )

    # per-tenant SLO rules over the merged union: the label filter keeps
    # each rule on its own tenant's series, so only acme trips
    from tpustream.obs.slo import TenantSLO, compile_tenant_slo

    engine = HealthEngine([
        r
        for t in tenants
        for r in compile_tenant_slo(
            t, TenantSLO(p99_ms=20.0, budget_window_s=60.0)
        )
    ])
    state = engine.evaluate(merged.snapshot()["series"], now_s=clk[0])
    by = {r["rule"]: r for r in state["rules"]}
    assert by["slo_p99[acme]"]["level"] == "crit"
    assert by["slo_p99[acme]"]["labels"] == {"tenant": "acme"}
    assert by["slo_p99[globex]"]["level"] == "ok"
    assert by["slo_p99[initech]"]["level"] == "ok"


def test_sharded_adaptive_controller_output_parity_p8():
    """p=8 with the adaptive controller ticking at flood rate: sink
    output identical to the controller-off run, and the controller left
    its audit trail (series + at least one decision event)."""
    _, out_off = _run(parallelism=8)

    cfg = StreamConfig(
        parallelism=8,
        batch_size=40,
        key_capacity=64,
        print_parallelism=1,
        obs=ObsConfig(
            enabled=True, adaptive=True, snapshot_interval_s=1e-4,
            adaptive_cooldown_ticks=0,
        ),
    )
    env = StreamExecutionEnvironment(cfg)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    h = build_et(env, env.add_source(ReplaySource(LINES))).collect()
    env.execute("obs-sharded-adaptive")
    out_on = sorted((t.f0, round(t.f1, 12)) for t in h.items)
    assert out_on == out_off  # depth moves never change results

    names = {
        s["name"]
        for s in env.metrics.obs_snapshot()["metrics"]["series"]
    }
    for want in (
        "controller_async_depth", "controller_fetch_group",
        "controller_h2d_depth", "controller_decisions_total",
    ):
        assert want in names, want
    evs = [
        e for e in env.metrics.job_obs.flight.events()
        if e["kind"] == "controller_decision"
    ]
    assert evs, "flood-rate ticks must produce at least one decision"
