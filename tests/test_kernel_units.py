"""Per-kernel unit tests (SURVEY.md §4: "unit tests per kernel — parse,
keyed max, pane assignment, watermark monotonicity per the spec at
chapter3/README.md:380-396").

Each test checks a device kernel against a plain-Python record-at-a-time
reference implementation on randomized inputs.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from tpustream.ops import panes as P
from tpustream.ops import sessions as S
from tpustream.ops.rolling import init_rolling_state, make_combiner, rolling_step
from tpustream.ops.segments import (
    segment_tails,
    segmented_scan,
    sort_by_key,
)


# ---------------------------------------------------------------- panes ----

def test_ring_spec_covers_window_plus_horizon():
    spec = P.make_ring_spec(
        size_ms=300_000, slide_ms=5_000, delay_ms=60_000, allowed_lateness_ms=0
    )
    assert spec.pane_ms == 5_000
    assert spec.panes_per_window == 60
    # ring must hold the window plus the out-of-orderness horizon
    assert spec.n_slots >= 60 + 12
    assert spec.n_fire_candidates == spec.n_slots + 60


def test_pane_assignment_and_last_window_end():
    spec = P.make_ring_spec(60_000, 15_000, 0, 0)  # 1-min window, 15-s slide
    ts = jnp.asarray([0, 14_999, 15_000, 59_999, 60_000], dtype=jnp.int64)
    assert list(np.asarray(P.pane_of(ts, spec.pane_ms))) == [0, 0, 1, 3, 4]
    # last window containing ts is [e-size, e) with the largest aligned e > ts
    ends = np.asarray(P.last_window_end(ts, spec))
    for t, e in zip(np.asarray(ts), ends):
        assert e % 15_000 == 0
        assert e - 60_000 <= t < e, (t, e)
        # e is maximal: the next slide's window would start after ts
        assert e + 15_000 - 60_000 > t


def test_late_mask_matches_flink_contract():
    spec = P.make_ring_spec(60_000, 60_000, 0, 0)  # tumbling 1 min
    # record at t=30s belongs to window [0,60s) which fires once wm >= 59999
    ts = jnp.asarray([30_000], dtype=jnp.int64)
    assert not bool(P.late_mask(ts, jnp.int64(59_998), 0, spec)[0])
    assert bool(P.late_mask(ts, jnp.int64(59_999), 0, spec)[0])
    # allowed lateness extends the live horizon
    assert not bool(P.late_mask(ts, jnp.int64(59_999), 10_000, spec)[0])
    assert bool(P.late_mask(ts, jnp.int64(69_999), 10_000, spec)[0])


def test_fire_candidates_fire_exactly_once_per_boundary():
    spec = P.make_ring_spec(300_000, 5_000, 60_000, 0)
    hi = jnp.int64(500)  # newest pane seen: stream has reached ~2_500_000 ms
    # wm trails hi by the 60s delay (the realistic operating point; panes
    # further back have rotated out of the ring and are no longer candidates)
    fired_ends = []
    wm_lo = jnp.int64(2_400_000)
    for wm_hi in range(2_400_000, 2_500_000, 7_000):  # advance in odd steps
        _, ends, fire = P.fire_candidates(hi, wm_lo, jnp.int64(wm_hi), spec)
        fired_ends.extend(np.asarray(ends)[np.asarray(fire)].tolist())
        wm_lo = jnp.int64(wm_hi)
        last = wm_hi
    # every fired end is slide-aligned, fired exactly once, and the set is
    # exactly the slide boundaries e with e-1 in (2_400_000, last]
    assert len(fired_ends) == len(set(fired_ends))
    expect = [
        e for e in range(0, 3_000_000, 5_000) if 2_400_000 < e - 1 <= last
    ]
    assert sorted(fired_ends) == expect


def test_retarget_clears_stale_slots_and_counts_unfired():
    spec = P.make_ring_spec(10_000, 10_000, 0, 0, slack=2)
    n = spec.n_slots
    cnt = jnp.ones((1, n), dtype=jnp.int32)  # one record in every slot
    acc = [jnp.full((1, n), 7.0)]
    init = [jnp.zeros((1, n))]
    slot_pane = P.slot_targets(jnp.int64(n - 1), spec)  # ring at panes [0, n)
    # jump far ahead: every slot becomes stale
    hi = jnp.int64(10 * n)
    wm = jnp.int64(0)  # nothing has fired
    acc2, cnt2, tgt, evicted = P.retarget(acc, cnt, slot_pane, hi, wm, spec, init)
    assert int(evicted) == n  # all n records were evicted before firing
    assert int(np.asarray(cnt2).sum()) == 0
    assert float(np.asarray(acc2[0]).sum()) == 0.0
    # same jump but wm already past every stale window end: nothing "unfired"
    wm_done = jnp.int64((n + spec.panes_per_window) * spec.pane_ms)
    _, _, _, evicted2 = P.retarget(acc, cnt, slot_pane, hi, wm_done, spec, init)
    assert int(evicted2) == 0


def test_compact_matches_numpy_and_counts_overflow():
    rng = np.random.default_rng(3)
    mask = rng.random(4096) < 0.3
    vals = rng.integers(0, 1000, 4096)
    capacity = 256
    idx, valid, overflow, (out,) = P.compact(
        jnp.asarray(mask), [jnp.asarray(vals)], capacity
    )
    want = vals[mask]
    got = np.asarray(out)[np.asarray(valid)]
    assert list(got) == list(want[:capacity])
    assert int(overflow) == max(0, mask.sum() - capacity)


# ------------------------------------------------------------- segments ----

def test_segmented_scan_matches_python_reference():
    rng = np.random.default_rng(0)
    n, k = 512, 13
    keys = rng.integers(0, k, n).astype(np.int32)
    vals = rng.random(n).astype(np.float32)
    valid = rng.random(n) < 0.9

    perm, sk, sv, seg_starts = sort_by_key(
        jnp.asarray(keys), jnp.asarray(valid), max_key=k
    )
    scanned = segmented_scan(
        (jnp.asarray(vals)[perm],), seg_starts, lambda a, b: (a[0] + b[0],)
    )[0]

    # reference: per-key running sum in arrival order
    run = {}
    want = np.zeros(n, dtype=np.float64)
    for i in range(n):
        if valid[i]:
            run[keys[i]] = run.get(keys[i], 0.0) + vals[i]
            want[i] = run[keys[i]]
    inv = np.empty(n, dtype=np.int64)
    inv[np.asarray(perm)] = np.arange(n)
    got = np.asarray(scanned)[inv]
    np.testing.assert_allclose(got[valid], want[valid], rtol=1e-5)

    # tails: exactly one per present key among valid rows
    tails = np.asarray(segment_tails(seg_starts) & sv)
    tail_keys = np.asarray(sk)[tails]
    assert sorted(tail_keys.tolist()) == sorted(set(keys[valid].tolist()))


# -------------------------------------------------------------- rolling ----

def _rolling_reference(kind, pos, batches, n_cols):
    """Record-at-a-time Flink-semantics reference (chapter2/README.md:52-66)."""
    state = {}
    out = []
    for keys, cols, valid in batches:
        emis = [np.zeros(len(keys), dtype=np.float64) for _ in range(n_cols)]
        for i in range(len(keys)):
            if not valid[i]:
                continue
            rec = tuple(c[i] for c in cols)
            k = keys[i]
            if k not in state:
                state[k] = rec
            else:
                cur = list(state[k])
                if kind == "max":
                    cur[pos] = max(cur[pos], rec[pos])
                elif kind == "min":
                    cur[pos] = min(cur[pos], rec[pos])
                elif kind == "sum":
                    cur[pos] = cur[pos] + rec[pos]
                elif kind == "max_by":
                    if rec[pos] > cur[pos]:
                        cur = list(rec)
                elif kind == "min_by":
                    if rec[pos] < cur[pos]:
                        cur = list(rec)
                state[k] = tuple(cur)
            for c in range(n_cols):
                emis[c][i] = state[k][c]
        out.append(emis)
    return out


@pytest.mark.parametrize("kind", ["max", "min", "sum", "max_by", "min_by"])
def test_rolling_matches_reference_across_batches(kind):
    rng = np.random.default_rng(42)
    kcap, b, nb = 17, 128, 3
    combine = make_combiner(kind, 1)
    state = init_rolling_state(kcap, ["str", "f64"])

    batches = []
    for _ in range(nb):
        keys = rng.integers(0, kcap, b).astype(np.int32)
        c0 = rng.integers(0, 100, b).astype(np.int32)
        c1 = np.round(rng.random(b) * 100, 1).astype(np.float64)
        valid = rng.random(b) < 0.85
        batches.append((keys, (c0, c1), valid))

    want = _rolling_reference(kind, 1, batches, 2)
    for (keys, cols, valid), w in zip(batches, want):
        state, emis_sorted, sv, sk, inv = rolling_step(
            state,
            jnp.asarray(keys),
            tuple(jnp.asarray(c) for c in cols),
            jnp.asarray(valid),
            combine,
            ["str", "f64"],
        )
        inv = np.asarray(inv)
        for c in range(2):
            arrival = np.asarray(emis_sorted[c])[inv]
            np.testing.assert_allclose(
                arrival[valid], w[c][valid], rtol=1e-5
            )


# pairwise cover of (kind x key_col x pos x compact) instead of the
# full 24-point product: the axes select independent code paths
# (combiner intrinsic / key-emission fast path / i64-two-plane vs f64
# leaf / 32-bit layout), so every pair of settings appears at least
# once while the suite runs 9 points, not 24 (gate budget, r4 next #7)
@pytest.mark.parametrize(
    "kind,key_col,pos,compact_mode",
    [
        ("max", None, 2, "none"),
        ("min", None, 2, "none"),
        ("sum", None, 2, "none"),
        ("max", 0, 1, "none"),
        ("sum", 0, 2, "none"),
        ("min", 0, 1, "none"),
        ("max", None, 1, "agg"),
        ("sum", 0, 1, "agg"),
        ("min", None, 2, "agg"),
    ],
)
def test_rolling_commutative_fast_path_matches_oracle(
    kind, key_col, pos, compact_mode
):
    """The max/min/sum fast path (single-column scan, key-column
    reconstruction, cond-deferred new-key bookkeeping) must match the
    record-at-a-time oracle batch by batch — including batches with no
    new keys at all, which exercise the steady-state cond branch.
    ``pos=1`` aggregates an i64 leaf, covering the two-word-plane
    lo/hi pack-and-scatter of the aggregated column; ``compact_mode
    "agg"`` covers its single-plane 32-bit layout."""
    rng = np.random.default_rng(7)
    kinds = ["str", "i64", "f64", "bool"]
    kcap, b = 13, 96
    compact = (
        False if compact_mode == "none" else [i == pos for i in range(4)]
    )
    combine = make_combiner(kind, pos)
    state = init_rolling_state(kcap, kinds, compact)

    batches = []
    for it in range(5):
        # confine early batches to few keys so later batches are all-seen
        hi = kcap if it < 2 else 4
        keys = rng.integers(0, hi, b).astype(np.int32)
        c0 = keys.copy()
        c1 = rng.integers(-50, 50, b).astype(np.int64)
        c2 = np.round(rng.random(b) * 100, 1).astype(np.float64)
        c3 = rng.random(b) < 0.5
        valid = rng.random(b) < 0.9
        batches.append((keys, (c0, c1, c2, c3), valid))

    want = _rolling_reference(kind, pos, batches, 4)
    kw = {}
    if key_col is not None:
        kw = dict(key_col=0, key_emit=lambda s: s.astype(jnp.int32))
    for (keys, cols, valid), w in zip(batches, want):
        state, emis_sorted, sv, sk, inv = rolling_step(
            state,
            jnp.asarray(keys),
            tuple(jnp.asarray(c) for c in cols),
            jnp.asarray(valid),
            combine,
            kinds,
            compact,
            rolling_kind=kind,
            rolling_pos=pos,
            **kw,
        )
        inv = np.asarray(inv)
        for c in range(4):
            arrival = np.asarray(emis_sorted[c])[inv]
            np.testing.assert_allclose(
                arrival[valid].astype(np.float64),
                w[c][valid].astype(np.float64),
                rtol=1e-5,
            )


@pytest.mark.parametrize("kind", ["max", "min", "sum"])
def test_rolling_fast_path_sentinel_occupancy_matches_oracle(kind):
    """sentinel_leaf derives `seen` from a keep-first STR plane
    initialized to -1 (interned ids are >= 0) — must be exact through
    new-key and steady-state batches, with the seen plane untouched."""
    rng = np.random.default_rng(11)
    kinds = ["str", "str", "f64"]
    kcap, b, pos = 13, 96, 2
    combine = make_combiner(kind, pos)
    state = init_rolling_state(kcap, kinds, sentinel_leaf=1)

    batches = []
    for it in range(5):
        hi = kcap if it < 2 else 4
        keys = rng.integers(0, hi, b).astype(np.int32)
        c0 = keys.copy()
        c1 = rng.integers(0, 50, b).astype(np.int32)  # interned ids >= 0
        c2 = np.round(rng.random(b) * 100, 1).astype(np.float64)
        valid = rng.random(b) < 0.9
        batches.append((keys, (c0, c1, c2), valid))

    want = _rolling_reference(kind, pos, batches, 3)
    for (keys, cols, valid), w in zip(batches, want):
        state, emis_sorted, sv, sk, inv = rolling_step(
            state,
            jnp.asarray(keys),
            tuple(jnp.asarray(c) for c in cols),
            jnp.asarray(valid),
            combine,
            kinds,
            rolling_kind=kind,
            rolling_pos=pos,
            key_col=0,
            key_emit=lambda s: s.astype(jnp.int32),
            sentinel_leaf=1,
        )
        inv = np.asarray(inv)
        for c in range(3):
            arrival = np.asarray(emis_sorted[c])[inv]
            np.testing.assert_allclose(
                arrival[valid].astype(np.float64),
                w[c][valid].astype(np.float64),
                rtol=1e-6,
            )
    # the dedicated seen plane stays cold on the sentinel path
    assert not np.asarray(state["seen"]).any()


# ------------------------------------------------------------- sessions ----

def test_session_runs_link_and_fire_propagation():
    gap = 10_000
    # panes of exactly `gap`; occupancy pattern: [A A gap B] for one key
    occ = jnp.asarray([[True, True, False, True]])
    mn = jnp.asarray([[1_000, 10_500, S.TS_MAX, 32_000]], dtype=jnp.int64)
    mx = jnp.asarray([[2_000, 11_000, S.W0, 33_000]], dtype=jnp.int64)
    link, run_end = S.session_runs(occ, mn, mx, gap)
    # pane1 joins pane0 (10_500 - 2_000 < gap); pane3 starts a new run
    assert np.asarray(link).tolist() == [[False, True, False, False]]
    assert np.asarray(run_end).tolist() == [[False, True, False, True]]
    # firing run-ends propagates to every member of the run
    fire_end = np.asarray(run_end) & np.array([[False, True, False, False]])
    fired = S.propagate_to_run(jnp.asarray(fire_end), link)
    assert np.asarray(fired).tolist() == [[True, True, False, False]]


def test_session_runs_do_not_link_across_wide_gap():
    gap = 10_000
    occ = jnp.asarray([[True, True]])
    mn = jnp.asarray([[0, 19_500]], dtype=jnp.int64)
    mx = jnp.asarray([[500, 19_900]], dtype=jnp.int64)
    link, _ = S.session_runs(occ, mn, mx, gap)
    # adjacent panes but 19_500 - 500 >= gap: separate sessions
    assert np.asarray(link).tolist() == [[False, False]]


# ------------------------------------------------------------ watermark ----

def test_watermark_monotone_under_decreasing_timestamps():
    """The BoundedOutOfOrderness contract (chapter3/README.md:380-396):
    wm = max_seen_ts - delay and never retreats, exercised through the
    flagship compiled step with batches whose max ts DECREASES."""
    import __graft_entry__ as ge

    program, _ = ge._build_flagship(1, 64, 32)
    state = program.init_state()
    wms = []
    base = 1_566_957_600_000
    for step, hi_ms in enumerate([600_000, 300_000, 100_000, 700_000]):
        ts = jnp.asarray(
            base + np.linspace(0, hi_ms, 64).astype(np.int64), jnp.int64
        )
        cols = (
            ts // 1000,
            jnp.zeros(64, jnp.int32),
            jnp.full((64,), 100, jnp.int64),
        )
        state, _ = program._step(
            state, cols, jnp.ones(64, bool), ts, jnp.asarray(P.W0, jnp.int64)
        )
        wms.append(int(np.asarray(state["wm"])))
    assert wms == sorted(wms), "watermark retreated"
    # and it equals max_seen - delay (1 min) once data pushes it forward
    assert wms[-1] == base + 700_000 - 60_000
