"""Multi-host DCN backend: 2-process jax.distributed on CPU.

The reference's cluster surface is Flink's Akka/Netty runtime inherited
through the flink-streaming-java dependency (reference pom.xml:50-55);
the TPU-native equivalent is jax.distributed + XLA collectives over
DCN (parallel/distributed.py). This test spawns two REAL processes with
two virtual CPU devices each, joins them through the coordinator, and
runs (1) a cross-process allgather, (2) a reduction over a 4-device
global-sharded array, and (3) the framework's keyBy all_to_all exchange
under shard_map spanning both processes.
"""

import os
import socket
import subprocess
import sys
import textwrap

WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("JAX_COORDINATOR_ADDRESS", None)
    import numpy as np
    import jax
    import jax.numpy as jnp

    pid, port = int(sys.argv[1]), sys.argv[2]
    from tpustream.parallel import distributed
    from tpustream.parallel.mesh import AXIS

    distributed.initialize(
        coordinator=f"127.0.0.1:{port}", num_processes=2, process_id=pid
    )
    distributed.initialize()  # idempotent second call must be a no-op
    assert jax.process_count() == 2, jax.process_count()
    assert distributed.process_index() == pid
    assert distributed.is_coordinator() == (pid == 0)

    # (1) control+data plane: allgather across DCN
    from jax.experimental import multihost_utils

    got = multihost_utils.process_allgather(np.asarray([pid * 10 + 1]))
    assert got.ravel().tolist() == [1, 11], got

    # (2) global mesh over all 4 devices; cross-process reduction
    mesh = distributed.global_mesh()
    assert mesh.size == 4, mesh
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(mesh, P(AXIS))
    vals = np.arange(8, dtype=np.float64)
    arr = jax.make_array_from_callback(
        vals.shape, sharding, lambda idx: vals[idx]
    )
    total = jax.jit(
        lambda a: jnp.sum(a),
        out_shardings=NamedSharding(mesh, P()),
    )(arr)
    assert float(np.asarray(total)) == 28.0

    # (3) the framework's keyBy exchange spanning both processes:
    # every record must land on shard key % 4, none lost
    from tpustream.parallel.exchange import exchange_by_key

    B = 8  # per shard
    def step(keys, vals, valid, ts):
        cols, v, ts2, ovf = exchange_by_key(
            [keys, vals], valid, ts, keys, 4, B
        )
        owner_ok = jnp.all(
            jnp.where(v, cols[0] % 4 == jax.lax.axis_index(AXIS), True)
        )
        kept = jnp.sum(v).astype(jnp.int64)
        pairs_ok = jnp.all(jnp.where(v, cols[1] == cols[0] * 7, True))
        return (
            jax.lax.psum(kept, AXIS),
            jnp.logical_and(
                jax.lax.pmin(owner_ok.astype(jnp.int32), AXIS) > 0,
                jax.lax.pmin(pairs_ok.astype(jnp.int32), AXIS) > 0,
            ),
            jax.lax.psum(ovf, AXIS),
        )

    sm = jax.jit(
        jax.shard_map(
            step,
            mesh=mesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(), P(), P()),
        )
    )
    rng = np.random.default_rng(0)
    gkeys = rng.integers(0, 16, 32).astype(np.int32)
    mk = lambda a, sh: jax.make_array_from_callback(
        a.shape, sh, lambda idx: a[idx]
    )
    keys = mk(gkeys, sharding)
    valsg = mk((gkeys * 7).astype(np.int32), sharding)
    valid = mk(np.ones(32, bool), sharding)
    ts = mk(np.zeros(32, np.int64), sharding)
    kept, ok, ovf = sm(keys, valsg, valid, ts)
    assert int(np.asarray(kept)) + int(np.asarray(ovf)) == 32
    assert bool(np.asarray(ok))
    print(f"worker {pid}: ok")
    """
)


def test_two_process_dcn_collectives(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=280)
        outs.append(out)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"worker {i}: ok" in out


# ---------------------------------------------------------------------------
# one JOB, two hosts (VERDICT r2 next #2): execute_job spans two real
# processes over jax.distributed; the union of both hosts' sink output
# must equal a single-process run byte for byte
# ---------------------------------------------------------------------------

# 12 channels -> interned key ids 0..11 spread over all 8 shards, so
# BOTH processes own emitting keys (ids 0..3 would all sit on host 0)
# 48 lines = 3 full batches at batch_size 16: the minimum stream whose
# CHAINED jobs emit before EOS (stage 2's first 15 s rollup needs a
# stage-1 window-end-20s result, which needs ts 22s+ in the stream)
JOB_LINES = [f"{1000 + i * 500} ch{i % 12} {(i % 7) * 10 + 1}" for i in range(48)]

_DEFAULT_EPILOGUE = textwrap.dedent(
    """
    for r in run_job(lines):
        print("ROW\\t" + r)
    print(f"worker {pid}: ok")
    """
)


def _run_two_process_job(tmp_path, snippet, epilogue=None, extra_argv=()):
    """Spawn two jax.distributed processes running ``snippet`` +
    ``epilogue`` over JOB_LINES on stdin; returns (sorted ROW lines,
    per-process ROW counts). ``extra_argv`` appends to each worker's
    command line (available as sys.argv[3:])."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    worker = (
        textwrap.dedent(
            """
            import os, sys
            os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
            os.environ["JAX_PLATFORMS"] = "cpu"
            os.environ.pop("JAX_COORDINATOR_ADDRESS", None)
            pid, port = int(sys.argv[1]), sys.argv[2]
            from tpustream.parallel import distributed

            distributed.initialize(
                coordinator=f"127.0.0.1:{port}", num_processes=2, process_id=pid
            )
            import jax
            assert jax.process_count() == 2
            lines = sys.stdin.read().splitlines()
            """
        )
        + snippet
        + (epilogue if epilogue is not None else _DEFAULT_EPILOGUE)
    )
    script = tmp_path / "job_worker.py"
    script.write_text(worker)
    env = {
        k: v
        for k, v in os.environ.items()
        if k not in ("XLA_FLAGS", "JAX_PLATFORMS")
    }
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(__file__))
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(i), str(port), *extra_argv],
            stdin=subprocess.PIPE,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            env=env,
            text=True,
        )
        for i in range(2)
    ]
    # feed BOTH stdin pipes before waiting on either: the workers run
    # one SPMD program and block on each other's collectives
    for p in procs:
        p.stdin.write("\n".join(JOB_LINES))
        p.stdin.close()
    outs = []
    for p in procs:
        outs.append(p.stdout.read())
        p.wait(timeout=280)
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"job worker {i} failed:\n{out}"
        assert f"worker {i}: ok" in out
    per_proc_rows = [
        [
            line.split("\t", 1)[1]
            for line in out.splitlines()
            if line.startswith("ROW\t")
        ]
        for out in outs
    ]
    got = sorted(r for rows in per_proc_rows for r in rows)
    return got, per_proc_rows


CKPT_VARIANT_SNIPPET = textwrap.dedent(
    """
    def run_ckpt_job(lines, variant, ckdir=None, restore=None, parallelism=8):
        from tpustream import (
            BoundedOutOfOrdernessTimestampExtractor,
            StreamExecutionEnvironment,
            Time,
            TimeCharacteristic,
            Tuple2,
            Tuple3,
        )
        from tpustream.config import StreamConfig
        from tpustream.runtime.sources import ReplaySource

        class Ts(BoundedOutOfOrdernessTimestampExtractor):
            def __init__(self):
                super().__init__(Time.milliseconds(2000))

            def extract_timestamp(self, value):
                return int(value.split(" ")[0])

        def parse(line):
            p = line.split(" ")
            return Tuple3(int(p[0]), p[1], int(p[2]))

        def median(key, ctx, elements, out):
            vals = sorted(e.f2 for e in elements)
            out.collect(Tuple2(key, float(vals[len(vals) // 2])))

        add3 = lambda a, b: Tuple3(a.f0, a.f1, a.f2 + b.f2)
        cfg = dict(batch_size=16, key_capacity=64, parallelism=parallelism)
        if ckdir:
            # interval 2: each snapshot is a cross-process gather of
            # every state leaf — half the collective rounds, same
            # resume semantics (restore uses the latest snapshot)
            cfg.update(checkpoint_dir=ckdir, checkpoint_interval_batches=2)
        env = StreamExecutionEnvironment(StreamConfig(**cfg))
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        if restore:
            env.restore_from_checkpoint(restore)
        text = env.add_source(ReplaySource(lines))
        keyed = (
            text.assign_timestamps_and_watermarks(Ts()).map(parse).key_by(1)
        )
        # (the former "single" shape is retired: "chained" runs the
        # identical single-stage machinery as its stage 1)
        if variant == "chained":
            stream = (
                keyed.time_window(Time.seconds(5)).reduce(add3)
                .key_by(1).time_window(Time.seconds(15)).reduce(add3)
            )
        elif variant == "process_chained":
            stream = (
                keyed.time_window(Time.seconds(5)).process(median)
                .key_by(0).time_window(Time.seconds(15))
                .reduce(lambda p, q: Tuple2(p.f0, p.f1 + q.f1))
            )
        else:
            raise ValueError(variant)
        handle = stream.collect()
        env.execute("TwoHostCkptJob-" + variant)
        return [repr(t) for t in handle.items]
    """
)


CKPT_EPILOGUE = textwrap.dedent(
    """
    # per variant: phase 1 runs with per-batch snapshots; phase 2
    # resumes from the latest one. Per-process exactly-once: the
    # resumed run's emissions must be exactly the tail of phase 1's.
    # The single-stage window shape is dropped from the loop: "chained"
    # runs the identical single-stage machinery as its stage 1 plus
    # the chain glue (gate budget, VERDICT r4 next #7).
    import os
    base = sys.argv[3]
    for variant in ("chained", "process_chained"):
        ckdir = os.path.join(base, variant)
        os.makedirs(ckdir, exist_ok=True)
        r1 = run_ckpt_job(lines, variant, ckdir=ckdir)
        r2 = run_ckpt_job(lines, variant, restore=ckdir)
        assert len(r2) < len(r1), (variant, len(r1), len(r2))
        assert r2 == r1[len(r1) - len(r2):], (
            f"{variant}: resume is not the exact tail: {r2} vs {r1}"
        )
    """
)


MULTI_VARIANT_SNIPPET = textwrap.dedent(
    """
    def run_job(lines, variant, parallelism=8):
        from tpustream import (
            BoundedOutOfOrdernessTimestampExtractor,
            StreamExecutionEnvironment,
            Time,
            TimeCharacteristic,
            Tuple2,
            Tuple3,
        )
        from tpustream.api.windows import (
            EventTimeSessionWindows,
            TumblingProcessingTimeWindows,
        )
        from tpustream.config import StreamConfig

        from tpustream.runtime.sources import ReplaySource

        class Ts(BoundedOutOfOrdernessTimestampExtractor):
            def __init__(self):
                super().__init__(Time.milliseconds(2000))

            def extract_timestamp(self, value):
                return int(value.split(" ")[0])

        def parse(line):
            p = line.split(" ")
            return Tuple3(int(p[0]), p[1], int(p[2]))

        def median(key, ctx, elements, out):
            vals = sorted(e.f2 for e in elements)
            mid = len(vals) // 2
            med = (
                float(vals[mid]) if len(vals) % 2
                else (vals[mid - 1] + vals[mid]) / 2
            )
            out.collect(Tuple2(key, med))

        def spans(key, ctx, elements, out):
            out.collect(Tuple2(key, float(sum(e.f2 for e in elements))))

        # *_growth variants start at key_capacity 8 (< the 12 distinct
        # channels), forcing a mid-stream collective capacity doubling
        cap = 8 if variant.endswith("_growth") else 64
        env = StreamExecutionEnvironment(
            StreamConfig(batch_size=16, key_capacity=cap,
                         parallelism=parallelism,
                         alert_capacity=4096, strict_overflow=True)
        )
        env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
        text = env.add_source(ReplaySource(lines))
        keyed = (
            text.assign_timestamps_and_watermarks(Ts()).map(parse).key_by(1)
        )
        add3 = lambda a, b: Tuple3(a.f0, a.f1, a.f2 + b.f2)
        add2 = lambda a, b: Tuple2(a.f0, a.f1 + b.f1)
        if variant in ("rolling", "rolling_growth"):
            stream = keyed.max(2)
        elif variant == "count":
            stream = keyed.count_window(2).reduce(add3)
        elif variant == "process":
            # full-window process(): each process evaluates its OWN
            # shards' fires from locally fetched state
            stream = keyed.time_window(Time.seconds(5)).process(median)
        elif variant == "session_process":
            # exercises the replicated-scalar state fetch (hi/wm are
            # 0-d, pending_mark is key-sharded) in the multi-host
            # host-evaluation path
            stream = keyed.window(
                EventTimeSessionWindows.with_gap(Time.seconds(3))
            ).process(spans)
        elif variant == "chain_window":
            # SLIDING-window-fed chain: one record fans into multiple
            # windows, so the hand-off carries repeated (end) values —
            # emissions allgather in canonical (end, key) order and the
            # downstream SPMD stage sees the identical global batch
            # everywhere
            stream = (
                keyed.time_window(Time.seconds(5), Time.seconds(2))
                .reduce(add3)
                .key_by(1).time_window(Time.seconds(15)).reduce(add3)
            )
        elif variant == "chain_rolling":
            # rolling-fed multi-host chain: emissions merge across
            # processes by global post-exchange row index; record ts
            # forwards into the event-time downstream window
            stream = (
                keyed.max(2)
                .key_by(1).time_window(Time.seconds(5)).reduce(add3)
            )
        elif variant == "chain_count":
            # count-fed chain: GlobalWindow results have no event
            # timestamp, so the downstream windows in processing time
            stream = (
                keyed.count_window(2).reduce(add3)
                .key_by(1)
                .window(TumblingProcessingTimeWindows.of(Time.minutes(5)))
                .reduce(add3)
            )
        elif variant == "chain_process":
            # process()-fed chain: rows gather + merge across processes
            # and the downstream schema is inferred from the GLOBAL set
            stream = (
                keyed.time_window(Time.seconds(5)).process(median)
                .key_by(0).time_window(Time.seconds(15)).reduce(add2)
            )
        elif variant == "chain_session":
            # session-fed chain: merged-session fires carry variable
            # (end, key) order keys through the cross-process merge
            stream = (
                keyed.window(
                    EventTimeSessionWindows.with_gap(Time.seconds(3))
                ).reduce(add3)
                .key_by(1).time_window(Time.seconds(15)).reduce(add3)
            )
        elif variant == "chain_computed":
            # computed KeySelector on the chain stage: every process
            # derives + interns keys from the identical merged batch
            # (6 derived keys -> owner shards span both processes)
            stream = (
                keyed.time_window(Time.seconds(5)).reduce(add3)
                .key_by(lambda r: int(r.f1[2:]) % 6)
                .time_window(Time.seconds(15))
                .reduce(add3)
            )
        else:
            raise ValueError(variant)
        handle = stream.collect()
        env.execute("TwoHostVariantJob-" + variant)
        return [repr(t) for t in handle.items]
    """
)


def _variant_epilogue(variants):
    # rows ride the standard "ROW\t" channel with a "variant|" field so
    # _run_two_process_job's extraction needs no changes
    return textwrap.dedent(
        f"""
        for variant in {variants!r}:
            for r in run_job(lines, variant):
                print("ROW\\t" + variant + "|" + r)
        """
    )


def _check_variants(tmp_path, variants, ckdir):
    # ONE worker pair runs the full variant matrix AND the checkpoint/
    # resume matrix (CKPT_EPILOGUE): each process spawn + jax
    # .distributed init costs ~15 s serialized on this 1-core host, so
    # everything multi-host amortizes over a single pair (gate budget)
    got, per_proc_rows = _run_two_process_job(
        tmp_path,
        MULTI_VARIANT_SNIPPET + CKPT_VARIANT_SNIPPET,
        epilogue=_variant_epilogue(variants)
        + CKPT_EPILOGUE
        + 'print(f"worker {pid}: ok")\n',
        extra_argv=(str(ckdir),),
    )
    ns = {}
    exec(MULTI_VARIANT_SNIPPET, ns)
    for variant in variants:
        mine = sorted(
            r.split("|", 1)[1]
            for r in got
            if r.startswith(variant + "|")
        )
        # reference at parallelism 1: the multiset is parallelism-
        # invariant (sharded == single-chip equivalence is pinned by
        # the single-host mesh suites), and the p=1 programs compile in
        # a fraction of the p=8 ones — gate budget (VERDICT r4 next #7)
        expect = sorted(ns["run_job"](JOB_LINES, variant, parallelism=1))
        assert expect, f"single-process {variant} produced no output"
        assert mine == expect, f"{variant}: {mine} != {expect}"
        # the work actually split: no process emitted everything
        per_proc = [
            sum(1 for r in rows if r.startswith(variant + "|"))
            for rows in per_proc_rows
        ]
        assert all(n < len(expect) for n in per_proc), (variant, per_proc)


def test_two_process_program_families(tmp_path):
    """Every program family across two hosts in ONE worker pair (one
    process spawn + jax.distributed init amortizes over all variants —
    gate budget, VERDICT r4 next #7). Single-stage: rolling and
    tumbling-count (VERDICT r3 weak #5 — per-shard order buffers
    dispatch each process's own emissions), full-window process() (each
    process evaluates its OWN shards' fires from locally fetched
    state), session+process() (replicated-scalar state fetch), and
    mid-stream key-capacity growth (local-shard state migration,
    collective-aligned). Chains fed by every stateful stage family —
    sliding window, session, rolling, count, process(), computed-key
    re-key (VERDICT r3 next #1): each re-key hand-off reconstructs the
    single-process order across processes. Every union matches the
    single-process run.

    The same worker pair also runs the multi-host checkpoint/resume
    matrix (CKPT_EPILOGUE): a CHAINED job (both stages' states
    snapshot — VERDICT r3 next #1c; its stage 1 covers the
    single-stage window shape) and the three-way multi-host +
    process()-fed chain + checkpoint combination (the lazily-inferred
    downstream schema snapshots from the globally merged view, and the
    _gather_chain_rows collectives interleave with the snapshot's leaf
    gathers without desync); each variant's resumed emissions are the
    exact per-process tail of its original run. Afterwards, THIS
    process restores the pair's parallelism-8 chained snapshot alone
    at parallelism 4 (multi-host save -> single-host rescale restore,
    VERDICT r4 missing #1's last leg): exactly-once holds as a
    multiset (emission order is parallelism-dependent; the
    pre-snapshot emission multiset is batch-deterministic)."""
    ckdir = tmp_path / "ck"
    ckdir.mkdir()
    _check_variants(
        tmp_path,
        [
            "rolling", "count", "process", "session_process",
            "rolling_growth",
            "chain_window", "chain_session", "chain_rolling",
            "chain_count", "chain_process", "chain_computed",
        ],
        ckdir,
    )

    from tpustream.runtime.checkpoint import load_checkpoint

    ns = {}
    exec(CKPT_VARIANT_SNIPPET, ns)
    for variant in ("chained",):
        vdir = str(ckdir / variant)
        # the full reference runs at p=4 too (emission multisets are
        # parallelism-invariant; the rescale under test is the
        # snapshot's p=8 layout restoring into these p=4 programs)
        full = ns["run_ckpt_job"](JOB_LINES, variant, parallelism=4)
        ck = load_checkpoint(vdir)
        resumed = ns["run_ckpt_job"](
            JOB_LINES, variant, restore=vdir, parallelism=4
        )
        assert 0 < ck.emitted < len(full), (variant, ck.emitted, len(full))
        assert sorted(resumed) == sorted(full[ck.emitted:]), variant

# NOTE: the former standalone two-process sliding-window job test (its
# own worker spawn comparing the union against a parallelism-8
# single-process run) is retired: its coverage is transitive —
# chain_window's stage 1 runs the same multi-host sliding-window path
# in the families pack above, and p8-single-process == p1 equivalence
# is pinned by the single-host mesh suites (gate budget, r4 next #7).
