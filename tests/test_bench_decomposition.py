"""Tier-1 bench smoke: the full-path decomposition and H2D-bandwidth
phases run in tiny mode on CPU, so stage-timing regressions (a stage
key disappearing, the pipelined pass deadlocking, the bandwidth probe
reverting to its RTT-corrupted form) are caught without the full bench.
"""

import importlib.util
import json
import os

import pytest


@pytest.fixture(scope="module")
def bench():
    path = os.path.join(os.path.dirname(__file__), os.pardir, "bench.py")
    spec = importlib.util.spec_from_file_location("bench_smoke", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_decompose_full_path_tiny_mode(bench):
    d = bench.decompose_full_path(n_batches=2, bl=256, nkey=1024)
    s = d["stages_ms"]
    for key in (
        "parse_intern_ms", "pack_ms", "h2d_step_fetch_ms",
        "count_fetch_rtt_ms", "batch_total_sync_ms",
    ):
        assert key in s and s[key] >= 0, key
    assert d["rows_per_batch"] == 256
    assert d["sync_rows_per_s"] > 0
    assert d["binding_stage"] in ("parse_intern_ms", "h2d_step_fetch_ms")
    # the packed wire format must only ever shrink a row
    assert 0 < d["bytes_per_row_packed"] <= d["bytes_per_row_raw"]
    assert d["wire_bytes_per_row"] == d["bytes_per_row_packed"]
    # the pipelined pass ran and drained (deadlock here = no number)
    assert d["pipelined_ms_per_batch"] > 0
    assert d["pipelined_rows_per_s"] > 0


def test_decompose_controller_pass_tiny_mode(bench):
    """The controller-on pass reports converged knobs inside bounds and
    sink bytes identical to the controller-off pipelined pass — the
    adaptive loop may move depths, never results."""
    d = bench.decompose_full_path(n_batches=4, bl=256, nkey=1024)
    c = d["controller"]
    assert c is not None
    assert sorted(c["converged"]) == ["async_depth", "fetch_group", "h2d_depth"]
    for knob, val in c["converged"].items():
        lo, hi = c["bounds"][knob]
        assert lo <= val <= hi, (knob, val, lo, hi)
    assert c["decisions"] >= 0 and c["reverts"] >= 0
    assert c["ms_per_batch"] is None or c["ms_per_batch"] > 0
    # windows fired in both passes (the digest is of real emissions,
    # not two empty sinks agreeing) and the bytes match exactly
    empty = "e3b0c44298fc1c149afbf4c8996fb92427ae41e4649b934ca495991b7852b855"
    assert c["baseline_sha"] != empty
    assert c["output_sha"] == c["baseline_sha"]


def test_ingest_lane_sweep_tiny_mode(bench):
    """Phase I2 in tiny mode: the lane sweep runs end to end, every
    lane count reports a positive rate over the full line budget, and
    the merged column digests are byte-identical to the 1-lane run —
    the whole point of the sharded-ingestion contract."""
    d = bench.ingest_lane_sweep(
        lane_counts=(1, 2), nbuf=4, warm=1, bl=1024, nkey=1 << 12
    )
    assert [e["lanes"] for e in d["results"]] == [1, 2]
    base = d["results"][0]["sha256"]
    for e in d["results"]:
        assert e["lines_per_s"] > 0
        assert e["n_lines"] == d["lines_per_run"]
        assert e["sha256"] == base
        assert e["byte_identical_to_1_lane"]


def test_measure_h2d_reports_positive_bandwidth(bench):
    mb_s = bench.measure_h2d()
    assert mb_s > 0


def test_multitenancy_probe_tiny_mode(bench):
    """Phase T in tiny mode: two fleet sizes, each through one compiled
    program with a hot per-tenant rule write — throughput/cost keys
    present, oracle output intact, zero config_change recompiles."""
    d = bench.multitenancy_probe(
        tenant_counts=(1, 4), records_per_tenant=8, batch_size=16
    )
    assert [e["tenants"] for e in d["sweep"]] == [1, 4]
    for e in d["sweep"]:
        assert e["events_per_s"] > 0 and e["ms_per_batch"] > 0
        assert e["config_change_recompiles"] == 0
        assert e["updated_tenant_matches_oracle"]
    assert d["zero_config_change_recompiles"]
    assert d["all_outputs_match"]


def test_tenant_slo_probe_tiny_mode(bench):
    """Phase T SLO leg in tiny mode: an 8-tenant fleet with one tenant
    flooding 5x its quota — the flooder's error SLO goes CRIT with a
    burned budget, the other 7 tenants stay OK, and the /tenants.json
    view assembles."""
    d = bench.tenant_slo_probe(
        tenants=8, records_per_tenant=4, flood_factor=5, batch_size=16
    )
    assert d["tenants"] == 8
    assert d["events_per_s"] > 0
    # 20 offered, 4 admitted: 16/20 diverted
    assert d["flooder_error_rate"] == pytest.approx(0.8)
    assert d["flooder_level"] == "crit"
    assert d["flooder_budget_burn"] == pytest.approx(1.0)
    assert d["others_ok"] == 7
    assert d["tenants_json_scrape_ms"] >= 0


def test_ledger_overhead_probe_tiny_mode(bench):
    """Phase O3 in tiny mode: the ledger on/off legs both run, the
    collected rows stay byte-identical (the ledger never touches a
    record), every evaluated invariant's residual is exactly zero, no
    violation latched, and both sinks carry a digest anchor."""
    d = bench.ledger_overhead_probe()
    assert d["output_identical"]
    assert d["sink_digest_base"] == d["sink_digest_ledger"]
    assert d["edges_evaluated"] >= 3  # source, sink0, contents edges
    assert d["all_residuals_zero"]
    assert all(r == 0 for r in d["residuals"].values() if r is not None)
    assert d["violations"] == 0
    assert "sink0" in d["anchors"]
    a = d["anchors"]["sink0"]
    assert a["count"] > 0 and len(a["digest"]) == 64 and a["verifiable"]


def test_checkpoint_overhead_probe_tiny_mode(bench):
    """Phase C2 in tiny mode: both plane postures run at both state
    sizes, the sink output is byte-identical across them (the plane
    never touches results), the incremental leg reuses chunks and ships
    fewer bytes than the sync-full leg, and the comparable top-level
    barrier-stall scalar comes out."""
    d = bench.checkpoint_overhead_probe(sizes=(("small", 16), ("large", 64)))
    assert d["outputs_identical"]
    assert d["barrier_stall_ms"] > 0
    for label in ("small", "large"):
        s = d[label]
        assert s["outputs_identical"]
        sync, inc = s["sync_full"], s["async_incremental"]
        assert sync["snapshots"] > 0 and inc["snapshots"] > 0
        assert sync["barrier_stall_ms_p99"] > 0
        assert inc["barrier_stall_ms_p99"] > 0
        # sync-full rewrites everything every snapshot; the incremental
        # plane reuses stable chunks, so it must ship strictly less
        assert sync["bytes_written"] == sync["bytes_state"]
        assert inc["chunks_reused"] > 0
        assert inc["bytes_written"] < sync["bytes_written"]
        assert s["delta_bytes_ratio"] < 1.0


def test_compare_smoke_same_env(bench, tmp_path):
    """Schema-2 records minted on this host compare cleanly: the env
    fingerprint matches itself, per-phase deltas come out, and the CI
    gate stays green on an improvement."""
    env = bench._resources_module().collect_env_fingerprint().to_dict()
    rec = {
        "bench": "tpu-stream-monitor",
        "bench_schema": bench.BENCH_SCHEMA,
        "env": env,
        "value": 100.0,
        "round_detail": {
            "sync_rows_per_s": 1000.0,
            "ledger": {"overhead_pct": 2.0},
            "checkpointing": {"barrier_stall_ms": 8.0},
        },
    }
    old = tmp_path / "old.json"
    old.write_text(json.dumps(rec))
    new = tmp_path / "new.json"
    new.write_text(
        json.dumps(dict(rec, round_detail={
            "sync_rows_per_s": 1500.0,
            "ledger": {"overhead_pct": 1.0},
            "checkpointing": {"barrier_stall_ms": 4.0},
        }))
    )
    loaded = bench.load_bench_record(str(old))
    assert loaded["error"] is None
    assert loaded["schema"] == bench.BENCH_SCHEMA
    assert loaded["env"]["usable_cores"] >= 1
    cmp = bench.compare_records(loaded, bench.load_bench_record(str(new)))
    assert cmp["comparable"] is True
    assert any(d["phase"] == "sync_rows_per_s" for d in cmp["deltas"])
    # the ledger phase flattens in, and less overhead is an improvement
    assert any(
        d["phase"] == "ledger.overhead_pct" for d in cmp["deltas"]
    )
    assert any(
        d["phase"] == "ledger.overhead_pct" for d in cmp["improvements"]
    )
    # the checkpoint plane's barrier stall flattens in as a _ms metric,
    # so a smaller stall is an improvement, never a regression
    assert any(
        d["phase"] == "checkpointing.barrier_stall_ms"
        for d in cmp["improvements"]
    )
    assert bench.run_compare([str(old), str(new)], gate=True) == 0
