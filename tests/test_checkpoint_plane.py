"""The checkpoint plane (docs/recovery.md "The checkpoint plane"):
incremental chunked snapshots, tiered retention + crash-safe chunk GC,
pinned savepoints, restore drills, and writer/GC fault recovery.

The unit half drives ``write_snapshot`` directly with hand-built
``PendingSnapshot`` cuts (no device needed), pinning the byte-level
contracts: delta bytes scale with churn, GC only ever touches
unreferenced content-named chunks, retention keeps the newest N plus
every keep_every-th durable plus whatever ``latest`` names. The job
half runs real supervised jobs through the executor: savepoint
pinning/restore, drill verdicts on a rotted store, and recovery from
faults injected inside the writer and the GC sweep.
"""

import glob
import json
import os
import shutil

import numpy as np
import pytest

from tpustream import StreamExecutionEnvironment
from tpustream.config import ObsConfig, StreamConfig
from tpustream.runtime.checkpoint import (
    CHUNK_DIR,
    FORMAT_VERSION,
    GC_MARK,
    PendingSnapshot,
    _checksum,
    _prune,
    _read_meta,
    _read_npz,
    latest_checkpoint,
    load_checkpoint,
    restore_drill,
    validate_checkpoint,
    write_snapshot,
)
from tpustream.runtime.sources import ReplaySource
from tpustream.runtime.supervisor import fixed_delay
from tpustream.testing import FaultInjected, FaultInjector, FaultPoint

LINES = [
    f"15634520{i % 60:02d} 10.8.22.{i % 5} cpu{i % 3} {(i * 13) % 100}.5"
    for i in range(16)
]


# ---------------------------------------------------------------------------
# unit half: hand-built cuts through write_snapshot
# ---------------------------------------------------------------------------
def make_pending(leaves, source_pos, batches=1):
    """A minimal-but-valid cut: real leaves, the meta fields the writer
    and validators actually read."""
    leaves = [np.asarray(l) for l in leaves]
    return PendingSnapshot(
        leaves=leaves,
        meta={
            "version": FORMAT_VERSION,
            "kind": "checkpoint",
            "checksum": _checksum(leaves),
        },
        source_pos=source_pos,
        batches=batches,
    )


def base_leaves(n=8, size=1024, seed=7):
    rng = np.random.default_rng(seed)
    return [rng.integers(0, 255, size, dtype=np.int32) for _ in range(n)]


def chunk_files(directory):
    cdir = os.path.join(directory, CHUNK_DIR)
    if not os.path.isdir(cdir):
        return set()
    return {n for n in os.listdir(cdir) if n.endswith(".npy")}


def manifest_refs(path):
    return {r["chunk"] for r in _read_meta(path).get("chunks") or []}


def test_incremental_delta_scales_with_churn(tmp_path):
    """Churning 1 of 8 equal-size leaves between snapshots must ship
    roughly 1/8th of the state — the incremental contract. The bound is
    25% (the manifest and atomic-write overhead ride on top of the one
    rewritten chunk, never on the seven stable ones)."""
    d = str(tmp_path)
    leaves = base_leaves()
    r1 = write_snapshot(d, make_pending(leaves, 2), keep=5)
    assert r1["chunks_written"] == 8 and r1["chunks_reused"] == 0
    assert r1["bytes_delta"] == r1["bytes_total"]

    leaves[3] = leaves[3] + 1
    r2 = write_snapshot(d, make_pending(leaves, 4), keep=5)
    assert r2["chunks_written"] == 1 and r2["chunks_reused"] == 7
    assert r2["bytes_delta"] <= 0.25 * r2["bytes_total"]
    # both snapshots restore their exact leaves next to the shared store
    for pos, want in ((2, base_leaves()), (4, leaves)):
        _, got = _read_npz(os.path.join(d, f"ckpt-{pos:010d}.npz"))
        for a, b in zip(got, want):
            np.testing.assert_array_equal(a, b)


def test_unchanged_state_reuses_every_chunk(tmp_path):
    """A fully stable cut writes only the manifest: every leaf chunk is
    referenced from the first snapshot's store."""
    d = str(tmp_path)
    leaves = base_leaves()
    write_snapshot(d, make_pending(leaves, 2), keep=5)
    before = chunk_files(d)
    r = write_snapshot(d, make_pending(leaves, 4), keep=5)
    assert r["chunks_written"] == 0 and r["chunks_reused"] == 8
    assert chunk_files(d) == before
    manifest = os.path.getsize(os.path.join(d, "ckpt-0000000004.npz"))
    assert r["bytes_delta"] == manifest


def test_gc_deletes_only_unreferenced_chunks(tmp_path):
    """Pruning a snapshot orphans its unique chunks; the next write's GC
    deletes exactly those — never a still-referenced chunk, never a
    foreign (non-content-named) file — and clears its mark."""
    d = str(tmp_path)
    leaves = base_leaves()
    write_snapshot(d, make_pending(leaves, 2), keep=1)
    doomed = manifest_refs(os.path.join(d, "ckpt-0000000002.npz"))
    cdir = os.path.join(d, CHUNK_DIR)
    with open(os.path.join(cdir, "operator-notes.txt"), "w") as f:
        f.write("not a chunk\n")

    # all-new leaves: keep=1 prunes snapshot 2, orphaning all its chunks
    fresh = [l + 100 for l in leaves]
    r = write_snapshot(d, make_pending(fresh, 4), keep=1)
    assert r["pruned"] == 1
    assert r["gc_deleted"] == len(doomed)
    survivors = chunk_files(d)
    assert not any(f"{h}.npy" in survivors for h in doomed)
    assert manifest_refs(os.path.join(d, "ckpt-0000000004.npz")) == {
        n[:-4] for n in survivors
    }
    assert os.path.exists(os.path.join(cdir, "operator-notes.txt"))
    assert not os.path.exists(os.path.join(cdir, GC_MARK))
    assert validate_checkpoint(os.path.join(d, "ckpt-0000000004.npz")) is None


def test_gc_crash_between_mark_and_sweep_resumes(tmp_path):
    """A crash after the GC mark lands but before the unlink sweep
    leaves the doomed chunks on disk and the mark present; the next
    write's GC re-verifies the mark and finishes — no retained snapshot
    loses a chunk at any point."""
    d = str(tmp_path)
    leaves = base_leaves()
    write_snapshot(d, make_pending(leaves, 2), keep=1)

    def fault(point):
        if point == "checkpoint_gc":
            raise FaultInjected(point, 0)

    fresh = [l + 100 for l in leaves]
    with pytest.raises(FaultInjected):
        write_snapshot(d, make_pending(fresh, 4), keep=1, fault=fault)
    cdir = os.path.join(d, CHUNK_DIR)
    mark = os.path.join(cdir, GC_MARK)
    assert os.path.exists(mark)
    with open(mark) as f:
        doomed = set(json.load(f)["doomed"])
    assert doomed and doomed <= chunk_files(d)  # marked, NOT yet swept
    # the interrupted write itself completed (GC runs last): usable now
    assert validate_checkpoint(os.path.join(d, "ckpt-0000000004.npz")) is None

    r = write_snapshot(d, make_pending(fresh, 6), keep=1)
    assert not os.path.exists(mark)
    assert r["gc_deleted"] >= len(doomed)
    assert not (doomed & chunk_files(d))
    latest = latest_checkpoint(d)
    assert latest is not None and validate_checkpoint(latest) is None


def test_retention_tiers_keep_plus_durable(tmp_path):
    """keep=2 keep_every=3 over eight snapshots retains the newest two
    plus every third seq as durable — and every survivor's chunk chain
    is still complete after the interleaved GC."""
    d = str(tmp_path)
    leaves = base_leaves(n=4)
    for i in range(1, 9):
        leaves[0] = leaves[0] + 1  # churn one leaf per snapshot
        write_snapshot(
            d, make_pending(leaves, 2 * i), keep=2, keep_every=3
        )
    names = sorted(
        os.path.basename(p)
        for p in glob.glob(os.path.join(d, "ckpt-*.npz"))
    )
    # seqs 3 and 6 are durable; seqs 7, 8 are the newest two
    assert names == [
        "ckpt-0000000006.npz", "ckpt-0000000012.npz",
        "ckpt-0000000014.npz", "ckpt-0000000016.npz",
    ]
    for n in names:
        p = os.path.join(d, n)
        assert validate_checkpoint(p) is None, n
        assert _read_meta(p)["seq"] in (3, 6, 7, 8)


def test_prune_consults_latest_marker(tmp_path):
    """The marker-race regression: whatever ``latest`` names must
    survive pruning even when newer-named snapshots exist — a crash
    between write and marker refresh must never leave the marker
    dangling at a deleted file."""
    d = str(tmp_path)
    leaves = base_leaves(n=4)
    for pos in (2, 4, 6):
        write_snapshot(d, make_pending(leaves, pos), keep=5)
    # simulate the race: marker still names the OLDEST snapshot
    with open(os.path.join(d, "latest"), "w") as f:
        f.write("ckpt-0000000002.npz")
    assert _prune(d, keep=1) == 1  # only ckpt-4 is prunable
    kept = sorted(
        os.path.basename(p)
        for p in glob.glob(os.path.join(d, "ckpt-*.npz"))
    )
    assert kept == ["ckpt-0000000002.npz", "ckpt-0000000006.npz"]
    assert latest_checkpoint(d) is not None


def test_corrupt_chunk_fails_validation_and_falls_back(tmp_path):
    """A bit-flipped chunk breaks exactly the manifests that reference
    it: validate_checkpoint names the chunk, latest_checkpoint falls
    back to the older intact snapshot, and the restore drill flags the
    nominal-latest rot instead of silently falling back."""
    d = str(tmp_path)
    leaves = base_leaves()
    write_snapshot(d, make_pending(leaves, 2), keep=5)
    leaves[0] = leaves[0] + 1
    write_snapshot(d, make_pending(leaves, 4), keep=5)
    newest = os.path.join(d, "ckpt-0000000004.npz")
    older = os.path.join(d, "ckpt-0000000002.npz")
    unique = manifest_refs(newest) - manifest_refs(older)
    assert unique
    cpath = os.path.join(d, CHUNK_DIR, f"{unique.pop()}.npy")
    raw = bytearray(open(cpath, "rb").read())
    raw[-1] ^= 0xFF
    with open(cpath, "wb") as f:
        f.write(bytes(raw))

    assert "checksum mismatch" in validate_checkpoint(newest)
    assert validate_checkpoint(older) is None
    assert latest_checkpoint(d) == older
    drill = restore_drill(d)
    assert drill["ok"] is False and drill["path"] == newest
    assert "checksum mismatch" in drill["reason"]


def test_half_gc_store_fails_drill(tmp_path):
    """A referenced chunk going missing (lost file, over-eager manual
    cleanup) is the drill's other catch: the walk names the missing
    chunk rather than reporting a loadable snapshot."""
    d = str(tmp_path)
    write_snapshot(d, make_pending(base_leaves(), 2), keep=5)
    newest = os.path.join(d, "ckpt-0000000002.npz")
    victim = sorted(manifest_refs(newest))[0]
    os.unlink(os.path.join(d, CHUNK_DIR, f"{victim}.npy"))
    drill = restore_drill(d)
    assert drill["ok"] is False
    assert "missing chunk" in drill["reason"]
    assert latest_checkpoint(d) is None  # the only snapshot is broken


# ---------------------------------------------------------------------------
# job half: real executors over the plane
# ---------------------------------------------------------------------------
def run_job(
    items=LINES, ckdir=None, restore=None, injector=None, strategy=None,
    savepoint_tags=(), **over
):
    from tpustream.jobs.chapter2_max import build

    over.setdefault("batch_size", 4)
    cfg = StreamConfig(**over)
    if ckdir is not None:
        cfg = cfg.replace(
            checkpoint_dir=str(ckdir), checkpoint_interval_batches=1
        )
    if injector is not None:
        cfg = injector.install(cfg)
    env = StreamExecutionEnvironment(cfg)
    if strategy is not None:
        env.set_restart_strategy(strategy)
    if restore is not None:
        env.restore_from_checkpoint(restore)
    for tag in savepoint_tags:
        env.savepoint(tag)
    handle = build(env, env.add_source(ReplaySource(items))).collect()
    result = env.execute("plane-test")
    return env, handle.items, result


def test_savepoint_pinned_and_self_contained(tmp_path):
    """A requested savepoint lands at the next barrier, survives a
    retention policy that prunes everything else down to one snapshot,
    restores the exact output suffix, and — being self-contained —
    loads from a bare directory with no chunk store at all."""
    ckdir = tmp_path / "ck"
    env, full, _ = run_job(
        ckdir=ckdir, savepoint_tags=("pre-upgrade",), checkpoint_keep=1
    )
    assert len(env.savepoints) == 1
    sp = env.savepoints[0]
    assert os.path.basename(sp).startswith("savepoint-")
    assert "pre-upgrade" in os.path.basename(sp)
    assert os.path.exists(sp)  # outlived keep=1 pruning and GC
    assert validate_checkpoint(sp) is None
    # savepoints are pinned artifacts, never recovery candidates
    assert latest_checkpoint(str(ckdir)) != sp

    ck = load_checkpoint(sp)
    _, resumed, _ = run_job(restore=sp)
    assert resumed == full[ck.emitted:]

    exiled = tmp_path / "exiled" / os.path.basename(sp)
    os.makedirs(exiled.parent)
    shutil.copy(sp, exiled)
    assert validate_checkpoint(str(exiled)) is None
    _, resumed2, _ = run_job(restore=str(exiled))
    assert resumed2 == full[ck.emitted:]


def test_savepoint_restores_across_rescale(tmp_path):
    """The savepoint's rescale story: state written at parallelism 1
    restores the identical suffix at parallelism 2."""
    import jax

    if jax.device_count() < 2:
        pytest.skip("needs >= 2 devices")
    ckdir = tmp_path / "ck"
    env, full, _ = run_job(ckdir=ckdir, savepoint_tags=("rescale",))
    sp = env.savepoints[0]
    ck = load_checkpoint(sp)
    _, resumed, _ = run_job(restore=sp, parallelism=2)
    # emission ORDER is parallelism-dependent; the exactly-once
    # multiset is not (test_checkpoint.py rescale_check idiom)
    assert sorted(map(repr, resumed)) == sorted(
        map(repr, full[ck.emitted:])
    )


@pytest.mark.parametrize("point,at", [
    ("checkpoint_write", 1),
    ("checkpoint_gc", 0),
])
def test_writer_and_gc_fault_recovery(tmp_path, point, at):
    """A crash inside the snapshot writer (mid-chunk, manifest not yet
    landed) or inside the GC sweep (mark landed, unlink pending) is a
    supervised restart like any other: the job restarts from the newest
    VALID snapshot, output stays byte-identical, and afterwards the
    store is coherent — every retained manifest's chunk chain walks."""
    _, full, _ = run_job()
    inj = FaultInjector(FaultPoint(point, at=at))
    ckdir = tmp_path / point
    # keep=1 with churn makes every barrier prune + GC, so the GC point
    # actually fires; async off keeps the fault on the barrier path
    _, out, _ = run_job(
        ckdir=ckdir, injector=inj, strategy=fixed_delay(3, 0.0),
        checkpoint_keep=1, checkpoint_async=False,
    )
    assert inj.fired == 1, point
    assert out == full, f"{point} recovery diverged"
    latest = latest_checkpoint(str(ckdir))
    assert latest is not None
    for p in glob.glob(os.path.join(str(ckdir), "ckpt-*.npz")):
        assert validate_checkpoint(p) is None, p


def test_async_writer_fault_surfaces_and_recovers(tmp_path):
    """The same writer crash in ASYNC mode: the failure crosses the
    writer thread and re-raises at a later barrier with its fault point
    intact, so supervision attributes and recovers identically."""
    _, full, _ = run_job()
    inj = FaultInjector(FaultPoint("checkpoint_write", at=1))
    env, out, res = run_job(
        ckdir=tmp_path, injector=inj, strategy=fixed_delay(3, 0.0),
        checkpoint_async=True, obs=ObsConfig(enabled=True),
    )
    assert inj.fired == 1
    assert out == full
    series = res.metrics.obs_snapshot()["metrics"]["series"]
    restarts = [s for s in series if s["name"] == "job_restarts_total"]
    assert sum(s["value"] for s in restarts) == 1
    assert restarts[0]["labels"]["cause"] == "checkpoint_write"


def test_device_fault_recovers_from_incremental_chain(tmp_path):
    """The tentpole composition: a device_step crash recovers from an
    async-incremental chunk chain — the restored run replays from a
    manifest snapshot and the output is byte-identical."""
    _, full, _ = run_job()
    inj = FaultInjector(FaultPoint("device_step", at=2))
    env, out, res = run_job(
        ckdir=tmp_path, injector=inj, strategy=fixed_delay(3, 0.0),
        checkpoint_async=True, checkpoint_incremental=True,
        obs=ObsConfig(enabled=True),
    )
    assert inj.fired == 1
    assert out == full
    series = res.metrics.obs_snapshot()["metrics"]["series"]
    replay = next(
        s for s in series if s["name"] == "recovery_replay_batches"
    )
    assert replay["value"] > 0
    # the restored-from snapshot really was a manifest
    restored = next(
        e for e in res.metrics.job_obs.flight.events()
        if e["kind"] == "job_restored"
    )
    assert _read_meta(restored["checkpoint"]).get("chunks")
    # the ledger's digest anchors verified the restore's sink rollback
    rst = res.metrics.obs_snapshot()["ledger"].get("restore")
    assert rst and rst["verified"] >= 1 and rst["mismatches"] == 0


def test_restore_drill_passes_on_intact_store(tmp_path):
    """Drills on a healthy store: verdict gauge 1, latency observed, no
    failure counter, no restore_drill_failed breadcrumb."""
    env, _, res = run_job(
        ckdir=tmp_path, restore_drill_interval_s=1e-6,
        obs=ObsConfig(enabled=True),
    )
    series = res.metrics.obs_snapshot()["metrics"]["series"]
    by_name = {s["name"]: s["value"] for s in series}
    assert by_name.get("restore_drill_verdict") == 1.0
    assert by_name.get("restore_drill_ms", {}).get("count", 0) >= 1
    assert "restore_drill_failures_total" not in by_name
    kinds = [e["kind"] for e in res.metrics.job_obs.flight.events()]
    assert "restore_drill_failed" not in kinds


def test_restore_drill_catches_rotted_store(tmp_path):
    """Bit-rot the whole chunk store between two runs of the same job:
    the second run's snapshots reference the (hash-matching, now
    corrupt) chunks, and its drills must catch the rot — verdict 0,
    failures counted, and a restore_drill_failed breadcrumb naming the
    reason — while the run's own output is unaffected."""
    _, full, _ = run_job(ckdir=tmp_path)
    cdir = os.path.join(str(tmp_path), CHUNK_DIR)
    for n in os.listdir(cdir):
        if not n.endswith(".npy"):
            continue
        p = os.path.join(cdir, n)
        raw = bytearray(open(p, "rb").read())
        raw[-1] ^= 0xFF
        with open(p, "wb") as f:
            f.write(bytes(raw))

    env, out, res = run_job(
        ckdir=tmp_path, restore_drill_interval_s=1e-6,
        obs=ObsConfig(enabled=True),
    )
    assert out == full  # drills observe; they never perturb the stream
    series = res.metrics.obs_snapshot()["metrics"]["series"]
    by_name = {s["name"]: s["value"] for s in series}
    assert by_name.get("restore_drill_verdict") == 0.0
    assert by_name.get("restore_drill_failures_total", 0) >= 1
    failed = [
        e for e in res.metrics.job_obs.flight.events()
        if e["kind"] == "restore_drill_failed"
    ]
    assert failed and "checksum mismatch" in failed[0]["reason"]
