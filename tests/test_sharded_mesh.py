"""Multi-chip SPMD tests on the 8-virtual-device CPU mesh: sharded jobs
must produce exactly the single-chip results (key-owner shards, ICI
all_to_all keyBy, pmax watermark)."""

import numpy as np
import pytest

import jax

try:  # jax >= 0.6
    shard_map = jax.shard_map
except AttributeError:  # older jax
    from jax.experimental.shard_map import shard_map

from tpustream import StreamExecutionEnvironment, TimeCharacteristic
from tpustream.config import StreamConfig
from tpustream.jobs.chapter2_max import build as build_max
from tpustream.jobs.chapter3_bandwidth_eventtime import build as build_et
from tpustream.runtime.sources import AdvanceProcessingTime, ReplaySource


def et_lines(n_keys=16, n_records=200):
    lines = []
    base_min = 0
    for i in range(n_records):
        minute = i // 20
        sec = (i * 7) % 60
        ch = f"www.ch{i % n_keys}.com"
        flow = 100 + (i % 13) * 10
        lines.append(f"2019-08-28T10:{minute:02d}:{sec:02d} {ch} {flow}")
    return lines


def run_et(lines, parallelism, batch_size=40, key_capacity=64, **cfg_overrides):
    env = StreamExecutionEnvironment(
        StreamConfig(
            parallelism=parallelism,
            batch_size=batch_size,
            key_capacity=key_capacity,
            print_parallelism=1,
            **cfg_overrides,
        )
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource(lines))
    h = build_et(env, text).collect()
    env.execute("BandwidthMonitorWithEventTime")
    return sorted((t.f0, round(t.f1, 12)) for t in h.items)


def test_sharded_event_time_window_matches_single_chip():
    lines = et_lines()
    single = run_et(lines, parallelism=1)
    sharded = run_et(lines, parallelism=8)
    assert len(single) > 0
    assert single == sharded


def test_sharded_four_shards():
    lines = et_lines(n_keys=7, n_records=120)
    assert run_et(lines, 1) == run_et(lines, 4)


def run_max(lines, parallelism, batch_size=40):
    env = StreamExecutionEnvironment(
        StreamConfig(
            parallelism=parallelism, batch_size=batch_size, key_capacity=64
        )
    )
    text = env.add_source(ReplaySource(lines))
    h = build_max(env, text).collect()
    env.execute("ComputeCpuMax")
    return h.items


def test_sharded_rolling_max_per_key_sequences_match():
    lines = [
        f"{i} 10.8.22.{i % 5} cpu{i % 3} {30 + ((i * 11) % 60)}.5"
        for i in range(100)
    ]
    single = run_max(lines, 1)
    sharded = run_max(lines, 8)
    assert len(single) == len(sharded) == 100

    def per_key(items):
        d = {}
        for t in items:
            d.setdefault(t.f0, []).append((t.f1, t.f2))
        return d

    assert per_key(single) == per_key(sharded)


def test_exchange_roundtrip_all_records():
    """Direct kernel test: every valid record lands on its owner exactly once."""
    from jax.sharding import PartitionSpec as P

    from tpustream.parallel.exchange import exchange_by_key
    from tpustream.parallel.mesh import AXIS, make_mesh

    s = 8
    b = 64
    mesh = make_mesh(s)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 37, size=b).astype(np.int32)
    vals = rng.normal(size=b)
    ts = rng.integers(0, 1000, size=b).astype(np.int64)
    valid = rng.random(b) > 0.2

    def core(keys, vals, ts, valid):
        cols, v, t, ovf = exchange_by_key(
            [keys, vals], valid, ts, keys, s, b // s
        )
        return cols[0], cols[1], t, v, jax.lax.psum(ovf, AXIS)

    f = jax.jit(
        shard_map(
            core,
            mesh=mesh,
            in_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS)),
            out_specs=(P(AXIS), P(AXIS), P(AXIS), P(AXIS), P()),
        )
    )
    k2, v2, t2, ok, ovf = jax.device_get(f(keys, vals, ts, valid))
    assert int(np.asarray(ovf).sum()) == 0
    got = sorted(
        (int(k), float(v), int(t))
        for k, v, t, o in zip(k2, v2, t2, ok)
        if o
    )
    want = sorted(
        (int(k), float(v), int(t))
        for k, v, t, o in zip(keys, vals, ts, valid)
        if o
    )
    assert got == want
    # ownership: received records' keys belong to the receiving shard
    rows_per_shard = len(k2) // s
    for d in range(s):
        sl = slice(d * rows_per_shard, (d + 1) * rows_per_shard)
        owned = k2[sl][ok[sl]]
        assert all(int(k) % s == d for k in owned)


def test_sharded_fast_reduce_path_matches_single_chip_exact():
    """The 32-bit scatter-reduce fast path with a per-step fire budget,
    sharded over 8 devices, must equal the exact single-chip results."""
    lines = et_lines()
    exact_single = run_et(lines, parallelism=1)
    fast_sharded = run_et(
        lines,
        parallelism=8,
        acc_dtype="int32",        # scatter-reduce fast path
        max_fires_per_step=2,     # exercise deferred fires sharded
    )
    assert len(exact_single) > 0
    assert exact_single == fast_sharded


# ---------------------------------------------------------------------------
# sharded ProcessWindowFunction (VERDICT round-1 item 4): the median job
# at parallelism N must match single-chip exactly
# (reference chapter2/README.md:177-196)
# ---------------------------------------------------------------------------
def median_lines(n_keys=6, n_records=90):
    lines = []
    for i in range(n_records):
        host = f"10.8.22.{i % n_keys}"
        usage = round(10.0 + ((i * 37) % 89) + 0.5, 1)
        lines.append(f"156345{i:04d} {host} cpu{i % 3} {usage}")
    return lines


def run_median(lines, parallelism, batch_size=40, **cfg_overrides):
    from tpustream.jobs.chapter2_median import build as build_median

    env = StreamExecutionEnvironment(
        StreamConfig(
            parallelism=parallelism,
            batch_size=batch_size,
            key_capacity=64,
            print_parallelism=1,
            process_buffer_capacity=64,
            **cfg_overrides,
        )
    )
    text = env.add_source(ReplaySource(lines))
    h = build_median(env, text).collect()
    env.execute("ComputeCpuMiddle")
    return env, sorted(round(float(v), 9) for v in h.items)


def test_sharded_process_window_matches_single_chip():
    lines = median_lines() + [AdvanceProcessingTime(61_000)]
    env1, single = run_median(lines, parallelism=1)
    env8, sharded = run_median(lines, parallelism=8)
    assert len(single) == 6  # one median per key
    assert single == sharded
    s1, s8 = env1.metrics.summary(), env8.metrics.summary()
    assert s1["window_fires"] == s8["window_fires"] == 6
    assert s8["buffer_overflow"] == 0


def test_sharded_process_window_multiple_windows_and_shard_counts():
    # records spread over two processing-time windows, 4 shards
    lines = (
        median_lines(n_keys=5, n_records=40)
        + [AdvanceProcessingTime(61_000)]
        + median_lines(n_keys=5, n_records=25)
        + [AdvanceProcessingTime(122_000)]
    )
    _, single = run_median(lines, parallelism=1)
    _, sharded = run_median(lines, parallelism=4, batch_size=16)
    assert len(single) == 10
    assert single == sharded
