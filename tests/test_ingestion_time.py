"""IngestionTime end-to-end golden test (VERDICT round-1 item 9).

The reference describes the three time notions at
chapter3/README.md:91-95; IngestionTime stamps each record with its
source-arrival time and then runs on the event-time machinery
(api/windows.py time_window_spec). Under the deterministic ReplaySource
the virtual processing-time clock IS the ingestion clock, so windows
bucket by arrival time regardless of any timestamp embedded in the line,
and the transcript replays exactly.
"""

from tpustream import StreamExecutionEnvironment, TimeCharacteristic
from tpustream.api.timeapi import Time
from tpustream.api.tuples import Tuple2
from tpustream.config import StreamConfig
from tpustream.jobs.chapter2_avg import AvgAggregate, parse
from tpustream.runtime.sources import AdvanceProcessingTime, ReplaySource


def run(items, **cfg):
    cfg.setdefault("batch_size", 2)
    env = StreamExecutionEnvironment(StreamConfig(key_capacity=16, **cfg))
    env.set_stream_time_characteristic(TimeCharacteristic.IngestionTime)
    text = env.add_source(ReplaySource(items))
    handle = (
        text.map(parse)
        .key_by(0)
        .time_window(Time.minutes(1))
        .aggregate(AvgAggregate())
        .collect()
    )
    env.execute("ingestion-avg")
    return handle.items, env.metrics.summary()


def test_ingestion_time_windows_bucket_by_arrival():
    # embedded timestamps are deliberately ancient/identical: ingestion
    # time must IGNORE them and bucket by the (virtual) arrival clock
    items = [
        "1563452000 10.8.22.1 cpu0 10.0",
        "1563452000 10.8.22.1 cpu0 20.0",
        AdvanceProcessingTime(61_000),       # arrival clock -> 61 s
        "1563452000 10.8.22.1 cpu0 99.0",    # second ingestion window
    ]
    out, s = run(items)
    # first window [0, 60s) fires once a 61s-stamped arrival is seen;
    # second window fires at end of stream
    assert out == [15.0, 99.0]
    assert s["window_fires"] == 2
    assert s["late_dropped"] == 0


def test_ingestion_time_two_keys_and_batch_invariance():
    items = [
        "1 10.8.22.1 cpu0 30.0",
        "1 10.8.22.2 cpu1 20.2",
        "1 10.8.22.1 cpu0 50.0",
        AdvanceProcessingTime(61_000),
        "1 10.8.22.1 cpu0 7.0",
    ]
    for bs in (1, 4):
        out, _ = run(items, batch_size=bs)
        assert sorted(out) == [7.0, 20.2, 40.0]
