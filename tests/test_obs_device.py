"""Device-side observability: the compile/recompile registry
(obs/compilation.py) and the HBM state-memory / key-skew accounting
(obs/memory.py), unit-level over a bare registry and end-to-end through
obs-enabled jobs."""

import jax.numpy as jnp
import pytest

from tpustream import StreamExecutionEnvironment, Time, TimeCharacteristic
from tpustream.config import ObsConfig, StreamConfig
from tpustream.jobs.chapter3_bandwidth_eventtime import build as build_et
from tpustream.obs import CompileObs, MetricsRegistry
from tpustream.obs.flightrecorder import FlightRecorder
from tpustream.obs.runtime import OperatorObs
from tpustream.obs.tracing import NULL_TRACER
from tpustream.runtime.sources import ReplaySource


# ---------------------------------------------------------------------------
# InstrumentedStep over a bare registry
# ---------------------------------------------------------------------------


def _compile_obs():
    reg = MetricsRegistry()
    group = reg.group(job="t", operator="op")
    flight = FlightRecorder(64)
    return CompileObs(OperatorObs(group, NULL_TRACER), flight), reg, flight


def _series(reg):
    return {(s.name, s.labels.get("cause")): s for s in reg.series()}


def test_instrumented_step_counts_compiles_and_causes():
    cobs, reg, flight = _compile_obs()

    def f(state, x):
        return state + x, state.sum()

    step = cobs.instrument(f, cause="initial", donate_argnums=())
    out1, s1 = step(jnp.zeros((4,)), jnp.ones((4,)))
    out2, _ = step(jnp.zeros((4,)), jnp.ones((4,)))  # same aval: cached
    assert out1.tolist() == out2.tolist() == [1.0] * 4

    s = _series(reg)
    assert s[("operator_compile_count", None)].value == 1
    assert s[("operator_recompile_count", None)].value == 0
    assert s[("operator_compile_wall_ms", None)].count == 1
    assert s[("operator_compile_wall_ms", None)].sum > 0

    # a new input signature is a recompile, attributed to shape change
    out3, _ = step(jnp.zeros((8,)), jnp.ones((8,)))
    assert out3.shape == (8,)
    s = _series(reg)
    assert s[("operator_compile_count", None)].value == 2
    assert s[("operator_recompile_count", None)].value == 1
    assert s[("operator_recompile_cause", "batch_shape_change")].value == 1
    assert s[("operator_compile_wall_ms", None)].count == 2

    events = [
        e for e in flight.dump()["events"] if e["kind"] == "program_compiled"
    ]
    assert [e["cause"] for e in events] == ["initial", "batch_shape_change"]
    assert all(e["wall_ms"] > 0 for e in events)


def test_instrumented_step_records_xla_cost_and_memory_gauges():
    cobs, reg, _ = _compile_obs()

    def f(x):
        return (x @ x.T).sum()

    step = cobs.instrument(f, cause="initial", donate_argnums=())
    step(jnp.ones((16, 16)))
    names = {s.name for s in reg.series()}
    # CPU provides both analyses; the gauges must be populated, not
    # merely minted
    by_name = {s.name: s for s in reg.series()}
    assert by_name["operator_compile_flops"].value > 0
    assert by_name["operator_compile_bytes_accessed"].value > 0
    assert "operator_compile_output_bytes" in names
    assert by_name["operator_compile_output_bytes"].value >= 0


def test_instrumented_step_fallback_on_lower_failure():
    cobs, reg, flight = _compile_obs()

    class _NoLower:
        """jit stand-in whose AOT path is broken but dispatch works."""

        def __call__(self, x):
            return x + 1

        def lower(self, *a):
            raise RuntimeError("no AOT here")

    step = cobs.instrument(lambda x: x + 1, cause="initial",
                           donate_argnums=())
    step._jit = _NoLower()
    assert step(jnp.zeros((2,))).tolist() == [1.0, 1.0]
    # the build still counted (via the dispatch wall time), and the
    # fallback left a breadcrumb; later calls skip the AOT path
    s = _series(reg)
    assert s[("operator_compile_count", None)].value == 1
    assert s[("operator_compile_instrument_fallback", None)].value == 1
    assert step._fallback
    kinds = [e["kind"] for e in flight.dump()["events"]]
    assert "compile_instrument_fallback" in kinds


# ---------------------------------------------------------------------------
# end-to-end: jobs populate the device-side series
# ---------------------------------------------------------------------------


def _lines(n=240, channels=3, hot=None):
    """Replay lines over ``channels`` distinct keys; ``hot`` (0..1)
    skews that fraction of rows onto channel 0."""
    out = []
    for i in range(n):
        if hot is not None and (i % 100) < hot * 100:
            ch = 0
        else:
            ch = i % channels
        out.append(
            f"2020-01-01T00:{i // 60:02d}:{i % 60:02d} ch{ch} 1234567"
        )
    return out


def _run(lines, **cfg_kw):
    cfg_kw.setdefault("batch_size", 16)
    cfg_kw.setdefault("key_capacity", 64)
    cfg = StreamConfig(obs=ObsConfig(enabled=True), **cfg_kw)
    env = StreamExecutionEnvironment(cfg)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    h = build_et(
        env,
        env.add_source(ReplaySource(lines)),
        size=Time.seconds(30),
        slide=Time.seconds(10),
        delay=Time.seconds(5),
    ).collect()
    env.execute("device-obs")
    snap = env.metrics.obs_snapshot()
    series = {}
    for s in snap["metrics"]["series"]:
        key = (s["name"], s["labels"].get("operator"))
        series.setdefault(key, []).append(s)
    return env, series


def _one(series, name, operator="window"):
    (s,) = series[(name, operator)]
    return s


def test_key_table_gauges_track_inserts():
    _, series = _run(_lines(n=240, channels=5))
    assert _one(series, "operator_key_table_capacity")["value"] == 64
    assert _one(series, "operator_key_table_occupancy")["value"] == 5
    assert _one(series, "operator_key_table_load_factor")["value"] == 5 / 64
    assert _one(series, "operator_key_cardinality")["value"] == 5
    assert _one(series, "operator_key_updates")["value"] == 240


def test_component_bytes_sum_to_hbm_total():
    _, series = _run(_lines())
    total = _one(series, "operator_hbm_state_bytes")["value"]
    assert total > 0
    comps = series[("operator_state_component_bytes", "window")]
    assert sum(s["value"] for s in comps) == total
    by_comp = {s["labels"]["component"]: s["value"] for s in comps}
    # the window program's footprint is dominated by its pane ring
    assert by_comp["pane_ring"] > by_comp.get("scalars", 0)


def test_hot_key_skew_gauges_flag_the_hot_key():
    env, series = _run(_lines(n=300, channels=10, hot=0.6))
    share = _one(series, "operator_hot_key_share")["value"]
    assert 0.55 < share < 0.75  # ch0 takes 60% of rows + its round-robin turns
    hot_id = int(_one(series, "operator_hot_key_id")["value"])
    # contrast with a uniform run: a balanced key mix has no dominant key
    _, balanced = _run(_lines(n=300, channels=10))
    bal_share = _one(balanced, "operator_hot_key_share")["value"]
    assert bal_share < share
    assert bal_share <= 0.2
    assert hot_id >= 0


def test_job_compile_registry_single_build():
    env, series = _run(_lines())
    assert _one(series, "operator_compile_count")["value"] == 1
    assert _one(series, "operator_recompile_count")["value"] == 0
    wall = _one(series, "operator_compile_wall_ms")["value"]
    assert wall["count"] == 1 and wall["sum"] > 0
    events = [
        e for e in env.metrics.job_obs.flight.dump()["events"]
        if e["kind"] == "program_compiled"
    ]
    assert len(events) == 1 and events[0]["cause"] == "initial"
    # the compile event carries the chain-complexity meta from
    # DeviceChain.describe() (this job's device pre-chain is empty —
    # parse runs host-side — but the fields must be present)
    assert events[0]["chain_ops"] == 0
    assert events[0]["chain_in_arity"] >= 1


@pytest.mark.slow
def test_key_capacity_growth_recompile_cause(tmp_path):
    """12 distinct keys against key_capacity=8 force exactly one 8->16
    growth; the rebuild surfaces as exactly one recompile whose cause
    is ``key_capacity_growth``, in both the series and the flight ring.
    The first half of the stream stays under capacity so the growth
    happens mid-job, AFTER the initial build — otherwise the very first
    compile would absorb the growth and no recompile would exist.

    Runs against a fresh per-test compilation cache: executing a
    cache-deserialized executable with donated buffers segfaults
    intermittently on this jax/XLA CPU build after a growth rebuild
    (the long-standing reason growth tests live in the slow tier), and
    a cold cache keeps the dispatch on the freshly-built in-memory
    executable."""
    import jax

    prev_cache = jax.config.jax_compilation_cache_dir
    jax.config.update("jax_compilation_cache_dir", str(tmp_path / "cc"))
    try:
        _growth_scenario()
    finally:
        jax.config.update("jax_compilation_cache_dir", prev_cache)


def _growth_scenario():
    lines = [
        f"2020-01-01T00:{i // 60:02d}:{i % 60:02d} "
        f"ch{i % (6 if i < 120 else 12)} 1234567"
        for i in range(240)
    ]
    env, series = _run(lines, key_capacity=8)
    assert _one(series, "operator_key_table_capacity")["value"] == 16
    assert _one(series, "operator_compile_count")["value"] == 2
    assert _one(series, "operator_recompile_count")["value"] == 1
    (cause_s,) = [
        s
        for s in series[("operator_recompile_cause", "window")]
        if s["labels"].get("cause") == "key_capacity_growth"
    ]
    assert cause_s["value"] == 1

    events = env.metrics.job_obs.flight.dump()["events"]
    compiled = [e for e in events if e["kind"] == "program_compiled"]
    growth_compiles = [
        e for e in compiled if e["cause"] == "key_capacity_growth"
    ]
    assert len(growth_compiles) == 1
    # the growth flight event itself carries the cause too
    grown = [e for e in events if e["kind"] == "key_capacity_grown"]
    assert len(grown) == 1
    assert grown[0]["cause"] == "key_capacity_growth"
    assert grown[0]["new_capacity"] == 16
