"""Randomized differential test: the windowed-aggregation program vs a
record-at-a-time Flink-semantics oracle.

The oracle replays the stream one batch at a time, maintaining the
bounded-out-of-orderness watermark (max_seen - delay, monotone —
chapter3/README.md:380-396), dropping records whose LAST window already
fired (late, chapter3/README.md:195-213), and firing every slide-aligned
window end the watermark crosses with the sum of its live records.
Random keys, timestamps, jitter, window geometry, batch sizes — both the
exact sorted-merge path and the 32-bit scatter-reduce fast path must
reproduce the oracle's (key, window_end, sum) multiset exactly.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from tpustream import StreamExecutionEnvironment, TimeCharacteristic
from tpustream.api.timeapi import Time
from tpustream.api.tuples import Tuple2, Tuple3
from tpustream.api.watermarks import BoundedOutOfOrdernessTimestampExtractor
from tpustream.api.windows import SlidingEventTimeWindows
from tpustream.config import StreamConfig
from tpustream.records import StringTable
from tpustream.runtime.plan import build_plan
from tpustream.runtime.sources import ReplaySource
from tpustream.runtime.step import build_program

BASE = 1_700_000_000_000  # ms


def build_program_for(size_s, slide_s, delay_s, acc_dtype, key_capacity, batch):
    class Ext(BoundedOutOfOrdernessTimestampExtractor):
        def __init__(self):
            super().__init__(Time.seconds(delay_s))

        def extract_timestamp(self, line):
            return int(line.split(" ")[0])

    env = StreamExecutionEnvironment(
        StreamConfig(
            batch_size=batch,
            key_capacity=key_capacity,
            alert_capacity=4096,
            acc_dtype=acc_dtype,
        )
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource([]))
    (
        text.assign_timestamps_and_watermarks(Ext())
        .map(lambda l: Tuple3(int(l.split(" ")[0]), l.split(" ")[1], int(l.split(" ")[2])))
        .key_by(1)
        .window(
            SlidingEventTimeWindows.of(
                Time.seconds(size_s), Time.seconds(slide_s)
            )
        )
        .reduce(lambda a, b: Tuple3(a.f0, a.f1, a.f2 + b.f2))
        .map(lambda t: Tuple2(t.f1, t.f2))  # like ch3: drops the first-seen
        .collect()                          # ts so only the sum is stored
    )
    plan = build_plan(env, env._sinks)
    if not plan.record_kinds:
        plan.record_kinds.extend(["i64", "str", "i64"])
        plan.tables.extend([None, StringTable(), None])
    return build_program(plan, env.config), plan


def oracle(batches, size_ms, slide_ms, delay_ms):
    """Record-at-a-time reference. Returns the multiset of
    (key, window_end, sum) fired across the whole stream + EOS flush."""
    wm = -(2**62)
    live = []  # (ts, key, flow) records accepted so far
    fired = set()  # window ends already fired (fire once per end)
    out = []

    def last_end(ts):
        return (ts + size_ms) // slide_ms * slide_ms

    def fire_through(new_wm):
        # every aligned end e with e-1 <= new_wm, not yet fired, that
        # could contain data
        if not live:
            ends = []
        else:
            lo = min(ts for ts, _, _ in live)
            hi = max(ts for ts, _, _ in live)
            first = (lo // slide_ms) * slide_ms + slide_ms
            ends = [
                e
                for e in range(first, last_end(hi) + slide_ms, slide_ms)
                if e - 1 <= new_wm and e not in fired
            ]
        for e in sorted(ends):
            fired.add(e)
            sums = {}
            for ts, k, f in live:
                if e - size_ms <= ts < e:
                    sums[k] = sums.get(k, 0) + f
            for k, s in sums.items():
                out.append((k, e, s))

    for batch in batches:
        wm_old = wm
        mx = max((ts for ts, _, _ in batch), default=None)
        if mx is not None:
            wm = max(wm, mx - delay_ms)
        for ts, k, f in batch:
            if last_end(ts) - 1 <= wm_old:
                continue  # late: all its windows fired
            live.append((ts, k, f))
        fire_through(wm)
    fire_through(2**62)  # EOS flush
    return sorted(out)


@pytest.mark.parametrize(
    "seed,acc_dtype",
    # both dtype paths on two seeds; the third seed covers the exact
    # path only (the 32-bit fast path's config space is narrower)
    [(0, "float64"), (0, "int32"), (1, "float64"), (1, "int32"),
     (2, "float64")],
)
def test_window_program_matches_oracle(seed, acc_dtype):
    rng = np.random.default_rng(seed)
    size_s = int(rng.choice([20, 30, 60]))
    slide_s = int(rng.choice([5, 10]))
    delay_s = int(rng.choice([0, 10, 30]))
    n_keys = int(rng.choice([3, 8, 16]))
    batch = 64
    n_batches = 10
    size_ms, slide_ms, delay_ms = size_s * 1000, slide_s * 1000, delay_s * 1000

    prog, plan = build_program_for(
        size_s, slide_s, delay_s, acc_dtype, max(16, n_keys), batch
    )
    assert prog.fast_reduce == (acc_dtype == "int32")
    step = jax.jit(prog._step)
    state = prog.init_state()

    t = BASE
    batches = []
    for _ in range(n_batches):
        ts = t + rng.integers(0, 20_000, batch) - rng.integers(0, delay_ms + 15_000, batch)
        keys = rng.integers(0, n_keys, batch).astype(np.int32)
        flow = rng.integers(1, 1000, batch)
        batches.append(list(zip(ts.tolist(), keys.tolist(), flow.tolist())))
        t += 15_000

    got = []

    def run_batch(recs, wm_lower, valid=True):
        nonlocal state
        ts = np.asarray([r[0] for r in recs], np.int64)
        cols = (
            jnp.asarray(ts),
            jnp.asarray([r[1] for r in recs], np.int32),
            jnp.asarray([r[2] for r in recs], np.int64),
        )
        state, em = step(
            state,
            cols,
            jnp.full(len(recs), valid, bool),
            jnp.asarray(ts),
            jnp.asarray(wm_lower, jnp.int64),
        )
        m = np.asarray(em["main"]["mask"])
        kc = np.asarray(em["main"]["cols"][0])
        sc = np.asarray(em["main"]["cols"][1])
        ec = np.asarray(em["main"]["window_end"])
        for j in np.nonzero(m)[0]:
            got.append((int(kc[j]), int(ec[j]), int(sc[j])))

    for b in batches:
        run_batch(b, -(2**62))
    # EOS: MAX watermark flush with an empty (all-invalid) batch
    run_batch([(0, 0, 0)] * batch, 2**62, valid=False)

    want = oracle(batches, size_ms, slide_ms, delay_ms)
    assert sorted(got) == want, (
        f"seed={seed} acc={acc_dtype} size={size_s}s slide={slide_s}s "
        f"delay={delay_s}s: {len(got)} fired vs oracle {len(want)}"
    )
