"""Golden transcript for the chapter-2 rolling max
(reference chapter2/README.md:52-66) plus semantics edge cases."""

from tpustream import StreamExecutionEnvironment
from tpustream.config import StreamConfig
from tpustream.jobs.chapter2_max import build
from tpustream.runtime.sources import ReplaySource


def run(lines, **cfg):
    env = StreamExecutionEnvironment(StreamConfig(**cfg))
    text = env.add_source(ReplaySource(lines))
    handle = build(env, text).collect()
    env.execute("ComputeCpuMax")
    return handle.items


def test_rolling_max_golden():
    out = run(
        [
            "1563452056 10.8.22.1 cpu0 80.5",
            "1563452050 10.8.22.1 cpu0 78.4",
            "1563452056 10.8.22.1 cpu0 99.9",
        ]
    )
    assert [repr(t) for t in out] == [
        "(10.8.22.1,cpu0,80.5)",
        "(10.8.22.1,cpu0,80.5)",
        "(10.8.22.1,cpu0,99.9)",
    ]


def test_rolling_max_keeps_first_seen_fields():
    # Flink max(pos) keeps NON-aggregated fields from the key's first record
    out = run(
        [
            "1 10.8.22.1 cpu0 50.0",
            "2 10.8.22.1 cpu7 60.0",   # higher usage but cpu field stays cpu0
            "3 10.8.22.1 cpu3 55.0",
        ]
    )
    assert [repr(t) for t in out] == [
        "(10.8.22.1,cpu0,50.0)",
        "(10.8.22.1,cpu0,60.0)",
        "(10.8.22.1,cpu0,60.0)",
    ]


def test_rolling_max_multi_key_and_batches():
    lines = []
    expected = {}
    rows = []
    vals = [(("h%d" % (i % 3)), 10.0 + ((i * 7) % 50)) for i in range(60)]
    for i, (h, v) in enumerate(vals):
        lines.append(f"{i} {h} cpu{i%2} {v}")
    # emulate semantics in python
    state = {}
    for i, (h, v) in enumerate(vals):
        if h not in state:
            state[h] = [h, f"cpu{i%2}", v]
        else:
            state[h][2] = max(state[h][2], v)
        rows.append(tuple(state[h]))
    out_big = run(lines)
    out_small = run(lines, batch_size=7)
    assert [t.values() for t in out_big] == rows
    assert out_big == out_small


def test_rolling_min_and_sum():
    lines = ["1 h1 c 5.0", "2 h1 c 3.0", "3 h1 c 4.0"]

    def run_kind(kind):
        from tpustream.jobs.chapter2_max import parse

        env = StreamExecutionEnvironment(StreamConfig())
        s = env.add_source(ReplaySource(lines)).map(parse).key_by(0)
        h = getattr(s, kind)(2).collect()
        env.execute("k")
        return [t.f2 for t in h.items]

    assert run_kind("min") == [5.0, 3.0, 3.0]
    assert run_kind("sum") == [5.0, 8.0, 12.0]


def test_rolling_max_by_replaces_whole_record():
    lines = ["1 h1 cpu0 50.0", "2 h1 cpu7 60.0", "3 h1 cpu3 55.0"]
    from tpustream.jobs.chapter2_max import parse

    env = StreamExecutionEnvironment(StreamConfig())
    h = (
        env.add_source(ReplaySource(lines))
        .map(parse)
        .key_by(0)
        .max_by(2)
        .collect()
    )
    env.execute("k")
    assert [repr(t) for t in h.items] == [
        "(h1,cpu0,50.0)",
        "(h1,cpu7,60.0)",
        "(h1,cpu7,60.0)",
    ]
