"""Session windows (gap-based merging windows).

The reference documents sessions at chapter3/README.md:412-428: windows
separated by >= gap of inactivity, firing when the watermark passes
``last_ts + gap - 1``. These tests drive the TPU session program
(tpustream/runtime/session_program.py) against a record-at-a-time oracle
implementing exactly those semantics, in event time and processing time,
single-chip and on the 8-virtual-device mesh.
"""

import numpy as np

from tpustream import (
    BoundedOutOfOrdernessTimestampExtractor,
    StreamExecutionEnvironment,
    Time,
    TimeCharacteristic,
    Tuple2,
)
from tpustream.api.windows import (
    EventTimeSessionWindows,
    ProcessingTimeSessionWindows,
)
from tpustream.config import StreamConfig
from tpustream.runtime.sources import AdvanceProcessingTime, ReplaySource

GAP_MS = 10_000
DELAY_MS = 2_000


def parse(value: str) -> Tuple2:
    items = value.split(" ")
    return Tuple2(items[1], int(items[2]))


class TsExtractor(BoundedOutOfOrdernessTimestampExtractor):
    def __init__(self):
        super().__init__(Time.milliseconds(DELAY_MS))

    def extract_timestamp(self, value: str) -> int:
        return int(value.split(" ")[0])


def session_oracle(records, gap_ms=GAP_MS, delay_ms=DELAY_MS):
    """Record-at-a-time Flink session semantics: per-key open sessions
    merge on overlap; fire when watermark >= last_ts + gap - 1; a record
    whose solo session has already closed is dropped as late."""
    wm = -(2**62)
    open_sessions = {}  # key -> list of [min_ts, max_ts, total]
    out = []

    def fire(new_wm):
        for key in sorted(open_sessions):
            keep = []
            for s in sorted(open_sessions[key]):
                if s[1] + gap_ms - 1 <= new_wm:
                    out.append((key, s[2], s[1] + gap_ms))
                else:
                    keep.append(s)
            open_sessions[key] = keep

    for ts, key, v in records:
        if ts + gap_ms - 1 <= wm:
            continue  # late
        sess = open_sessions.setdefault(key, [])
        merged = [ts, ts, v]
        rest = []
        for s in sess:
            if s[0] - gap_ms < merged[1] and merged[0] - gap_ms < s[1]:
                merged = [
                    min(merged[0], s[0]),
                    max(merged[1], s[1]),
                    merged[2] + s[2],
                ]
            else:
                rest.append(s)
        open_sessions[key] = rest + [merged]
        wm = max(wm, ts - delay_ms)
        fire(wm)
    fire(2**62)  # bounded stream end
    return sorted(out)


def run_session_job(lines, batch_size=1, parallelism=1, key_capacity=64):
    cfg = StreamConfig(
        batch_size=batch_size,
        key_capacity=key_capacity,
        alert_capacity=1024,
        parallelism=parallelism,
    )
    env = StreamExecutionEnvironment(cfg)
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource(lines))
    h = (
        text.assign_timestamps_and_watermarks(TsExtractor())
        .map(parse)
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(Time.milliseconds(GAP_MS)))
        .reduce(lambda a, b: Tuple2(a.f0, a.f1 + b.f1))
        .collect()
    )
    env.execute("SessionJob")
    return sorted((t.f0, t.f1) for t in h.items)


def lines_of(records):
    return [f"{ts} {key} {v}" for ts, key, v in records]


def test_single_session_fires_on_watermark():
    # one burst, then a record far enough ahead to close it
    recs = [
        (1_000, "a", 1),
        (4_000, "a", 2),
        (9_000, "a", 4),
        # 9_000 + 10_000 + DELAY -> watermark must pass 18_999
        (25_000, "a", 8),
    ]
    got = run_session_job(lines_of(recs))
    oracle = [(k, v) for k, v, _ in session_oracle(recs)]
    assert got == sorted(oracle)
    # first session is 1+2+4, second (EOS-fired) is 8
    assert got == [("a", 7), ("a", 8)]


def test_gap_splits_sessions_exactly():
    recs = [
        (0, "a", 1),
        (9_999, "a", 2),     # gap 9999 < 10000: same session
        (20_000, "a", 4),    # gap 10001 >= 10000: new session
        (29_999, "a", 8),    # same as previous
        (60_000, "a", 16),
    ]
    got = run_session_job(lines_of(recs))
    assert got == [("a", 3), ("a", 12), ("a", 16)]


def test_boundary_gap_exactly_equal_to_gap_splits():
    recs = [(0, "a", 1), (10_000, "a", 2), (50_000, "a", 4)]
    got = run_session_job(lines_of(recs))
    # 10_000 - 0 == gap: NOT merged (windows [0,10000) and [10000,20000)
    # touch but do not overlap in Flink)
    assert got == [("a", 1), ("a", 2), ("a", 4)]


def test_multiple_keys_independent_sessions():
    recs = [
        (0, "a", 1),
        (1_000, "b", 10),
        (5_000, "a", 2),
        (30_000, "b", 20),
        (31_000, "a", 4),
    ]
    got = run_session_job(lines_of(recs))
    oracle = [(k, v) for k, v, _ in session_oracle(recs)]
    assert got == sorted(oracle)
    assert got == [("a", 3), ("a", 4), ("b", 10), ("b", 20)]


def test_out_of_order_record_merges_sessions():
    # two bursts >= gap apart are separate sessions; an out-of-order
    # record lands between them while the first is still unfired
    # (watermark 11_000 < 11_999) and bridges both into one session
    recs = [
        (0, "a", 1),
        (2_000, "a", 2),
        (13_000, "a", 4),   # separate session; wm -> 11_000, nothing fires
        (7_000, "a", 8),    # bridges [0..2000] and [13000] into one
        (60_000, "a", 16),
    ]
    got = run_session_job(lines_of(recs))
    oracle = [(k, v) for k, v, _ in session_oracle(recs)]
    assert got == sorted(oracle)
    assert got == [("a", 15), ("a", 16)]


def test_late_record_dropped():
    recs = [
        (0, "a", 1),
        (50_000, "a", 2),   # wm -> 48_000; session [0,10000) fired
        (5_000, "a", 4),    # ts+gap-1 = 14_999 <= 48_000: late, dropped
        (90_000, "a", 8),
    ]
    got = run_session_job(lines_of(recs))
    oracle = [(k, v) for k, v, _ in session_oracle(recs)]
    assert got == sorted(oracle)
    assert ("a", 4) not in got and ("a", 5) not in got


def test_batched_matches_oracle_modulo_watermark_cadence():
    # randomized stream, one batch per record -> exact oracle match
    rng = np.random.default_rng(7)
    t = 0
    recs = []
    for _ in range(200):
        t += int(rng.integers(0, 15_000))
        key = str(rng.choice(["a", "b", "c"]))
        jitter = int(rng.integers(0, DELAY_MS))
        recs.append((max(0, t - jitter), key, int(rng.integers(1, 100))))
    got = run_session_job(lines_of(recs))
    oracle = sorted((k, v) for k, v, _ in session_oracle(recs))
    assert got == oracle


def test_sharded_session_matches_single_chip():
    rng = np.random.default_rng(3)
    t = 0
    recs = []
    for _ in range(150):
        t += int(rng.integers(0, 12_000))
        key = str(rng.choice(["a", "b", "c", "d", "e"]))
        recs.append((t, key, int(rng.integers(1, 50))))
    single = run_session_job(lines_of(recs), batch_size=8)
    sharded = run_session_job(
        lines_of(recs), batch_size=8, parallelism=8, key_capacity=64
    )
    assert sharded == single


def test_processing_time_sessions():
    env = StreamExecutionEnvironment(
        StreamConfig(batch_size=4, key_capacity=16, alert_capacity=64)
    )
    env.set_stream_time_characteristic(TimeCharacteristic.ProcessingTime)
    # a processing-time tick far past the gap closes the session
    text = env.add_source(
        ReplaySource(
            ["x a 1", "x a 2", AdvanceProcessingTime(100_000)],
            start_ms=1_000,
            ms_per_record=100,
        )
    )
    h = (
        text.map(lambda v: Tuple2(v.split(" ")[1], int(v.split(" ")[2])))
        .key_by(0)
        .window(ProcessingTimeSessionWindows.with_gap(Time.milliseconds(GAP_MS)))
        .reduce(lambda a, b: Tuple2(a.f0, a.f1 + b.f1))
        .collect()
    )
    env.execute("ProcSession")
    assert [(t.f0, t.f1) for t in h.items] == [("a", 3)]


def test_session_aggregate_function():
    from tpustream import AggregateFunction

    class CountAgg(AggregateFunction):
        def create_accumulator(self):
            return Tuple2("", 0)

        def add(self, value, accumulator):
            return Tuple2(value.f0, accumulator.f1 + 1)

        def get_result(self, accumulator):
            return accumulator.f1

        def merge(self, a, b):
            return Tuple2(a.f0, a.f1 + b.f1)

    recs = [(0, "a", 1), (3_000, "a", 1), (40_000, "a", 1)]
    env = StreamExecutionEnvironment(
        StreamConfig(batch_size=1, key_capacity=16, alert_capacity=64)
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource(lines_of(recs)))
    h = (
        text.assign_timestamps_and_watermarks(TsExtractor())
        .map(parse)
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(Time.milliseconds(GAP_MS)))
        .aggregate(CountAgg())
        .collect()
    )
    env.execute("SessionCount")
    assert sorted(h.items) == [1, 2]


def test_session_fast_path_matches_generic_path(monkeypatch):
    """Direct A/B: the scatter-reduce fast path (round 5) and the
    sorted-merge generic path must produce identical output on the same
    randomized stream (int sums — exact either way). The oracle tests
    cover semantics; this pins PATH equivalence, including the
    pane-relative int32 boundary storage."""
    from tpustream.runtime.session_program import SessionWindowProgram

    rng = np.random.default_rng(21)
    t = 0
    recs = []
    for _ in range(150):
        t += int(rng.integers(0, 12_000))
        key = str(rng.choice(["a", "b", "c", "d", "e"]))
        jitter = int(rng.integers(0, DELAY_MS))
        recs.append((max(0, t - jitter), key, int(rng.integers(1, 50))))

    fast = run_session_job(lines_of(recs), batch_size=8)

    orig = SessionWindowProgram._analyze_session_fast

    def force_generic(self):
        orig(self)
        assert self._sess_fast, "stream/job no longer fast-eligible"
        self._sess_fast = False
        self._rel_ts = False

    monkeypatch.setattr(
        SessionWindowProgram, "_analyze_session_fast", force_generic
    )
    generic = run_session_job(lines_of(recs), batch_size=8)
    assert fast == generic


def test_session_aggregate_keep_first_acc_stays_on_generic_path():
    """An AGGREGATE accumulator whose merge passes a leaf through is
    keep-first semantics, not a cell-invariant key — the scatter-reduce
    fast path must NOT classify it as the key leaf (a non-unique
    scatter-set would pick an arbitrary writer). Regression for the
    round-5 fast-path guard: acc = (first value seen, int total)."""
    from tpustream import AggregateFunction

    class FirstAndTotal(AggregateFunction):
        def create_accumulator(self):
            return Tuple2(-1, 0)

        def add(self, value, accumulator):
            import jax.numpy as jnp

            first = jnp.where(
                accumulator.f1 == 0, value.f1, accumulator.f0
            )
            return Tuple2(first, accumulator.f1 + value.f1)

        def get_result(self, accumulator):
            return accumulator

        def merge(self, a, b):
            return Tuple2(a.f0, a.f1 + b.f1)  # f0 = keep a's first

    recs = [(0, "a", 7), (1_000, "a", 3), (2_000, "a", 5), (40_000, "a", 1)]
    env = StreamExecutionEnvironment(
        StreamConfig(batch_size=1, key_capacity=16, alert_capacity=64)
    )
    env.set_stream_time_characteristic(TimeCharacteristic.EventTime)
    text = env.add_source(ReplaySource(lines_of(recs)))
    h = (
        text.assign_timestamps_and_watermarks(TsExtractor())
        .map(parse)
        .key_by(0)
        .window(EventTimeSessionWindows.with_gap(Time.milliseconds(GAP_MS)))
        .aggregate(FirstAndTotal())
        .collect()
    )
    env.execute("SessionFirstTotal")
    # first session: first=7 (arrival order), total=15; the 40 s record
    # opens a second session that fires at EOS with first=1, total=1
    assert sorted((t.f0, t.f1) for t in h.items) == [(1, 1), (7, 15)]
