"""H2D wire packing: lossless narrow formats and their demotion paths.

The executor ships int64 columns/timestamps as deltas against a
per-batch base (StreamConfig.h2d_compress) — uint16 deltas first under
the packed wire format (StreamConfig.packed_wire), int32 past a 2^16
span — and narrows float64 to exact-round-trip float32 and interned
string ids to int16. A batch whose valid rows no longer fit the narrow
form must demote that column down its chain PERMANENTLY — rebuilding
the jitted step mid-stream — with bit-exact results either way.
"""

import numpy as np

from tpustream import StreamExecutionEnvironment, Tuple2
from tpustream.config import StreamConfig
from tpustream.runtime.sources import ReplaySource


def parse(line: str) -> Tuple2:
    items = line.split(" ")
    return Tuple2(items[1], int(items[2]))


def run(lines, batch_size=4, parse_fn=parse, **cfg):
    env = StreamExecutionEnvironment(StreamConfig(batch_size=batch_size, **cfg))
    text = env.add_source(ReplaySource(lines))
    handle = (
        text.map(parse_fn)
        .key_by(0)
        .sum(1)
        .collect()
    )
    env.execute("h2d")
    return [tuple(t) for t in handle.items]


def test_mid_stream_span_overflow_demotes_exactly():
    # batch 1 fits int32 deltas; batch 2 spans > 2^31 (and includes
    # negatives); batch 3 returns to small values — all on the SAME
    # demoted column, exercising the one-time step rebuild
    big = 3 << 31
    lines = (
        ["1 a 5", "1 b 7", "1 a 11", "1 b 13"]
        + [f"1 a {big}", f"1 b {-big}", "1 a 17", "1 b 19"]
        + ["1 a 23", "1 b 29", "1 a 31", "1 b 37"]
    )
    got = run(lines)
    want = run(lines, h2d_compress=False)
    assert got == want
    # the rolling sums are exact through the demotion
    totals = {}
    expect = []
    for line in lines:
        _, k, v = line.split(" ")
        totals[k] = totals.get(k, 0) + int(v)
        expect.append((k, totals[k]))
    assert got == expect


def test_d16_span_overflow_demotes_to_d32_exactly():
    """Batch 1 fits uint16 deltas; batch 2 spans past 2^16 (but inside
    int32) so the column demotes d16 -> d32, one recompile; batch 3's
    small values ride the demoted d32 form — identical output to both
    unpacked configs."""
    lines = (
        ["1 a 5", "1 b 7", "1 a 11", "1 b 13"]
        + ["1 a 100000", "1 b 3", "1 a 200000", "1 b 4"]
        + ["1 a 23", "1 b 29", "1 a 31", "1 b 37"]
    )
    got = run(lines)
    assert got == run(lines, packed_wire=False)
    assert got == run(lines, h2d_compress=False, packed_wire=False)


def parse_float(line: str) -> Tuple2:
    items = line.split(" ")
    return Tuple2(items[1], float(items[2]))


def test_f32_inexact_value_demotes_exactly():
    """2^24 + 1 rounds in float32 (16777217 -> 16777216): the exact
    round-trip check must demote the float column to raw float64 for
    that batch and after — sums stay bit-exact."""
    lines = (
        ["1 a 1.5", "1 b 2.5", "1 a 0.25", "1 b 4.0"]  # all exact in f32
        + ["1 a 16777217.0", "1 b 0.5", "1 a 1.0", "1 b 2.0"]
        + ["1 a 0.125", "1 b 8.0", "1 a 16.0", "1 b 32.0"]
    )
    got = run(lines, parse_fn=parse_float)
    want = run(lines, parse_fn=parse_float, packed_wire=False)
    assert got == want
    totals = {}
    for line in lines:
        _, k, v = line.split(" ")
        totals[k] = totals.get(k, 0.0) + float(v)
    assert got[-1] == ("b", totals["b"]) and got[-2] == ("a", totals["a"])


def test_i16_id_overflow_demotes_exactly():
    """More than 2^15 distinct interned strings push key ids past
    int16: the id column demotes i16 -> raw int32 mid-stream and every
    key's sum survives. (Batch 4096 keeps this a ~9-step run.)"""
    n = (1 << 15) + 4096  # crosses 32767 in the final batches
    lines = [f"1 k{i} {i % 13}" for i in range(n)]
    cfg = dict(batch_size=4096, key_capacity=1 << 16, alert_capacity=4096)
    got = run(lines, **cfg)
    want = run(lines, packed_wire=False, **cfg)
    assert got == want
    assert got[-1] == (f"k{n - 1}", (n - 1) % 13)


def test_full_range_column_never_compresses():
    # min near -2^62 and max near 2^62 in ONE batch: the span check must
    # not wrap (it is computed in Python ints) and the column ships raw
    lo, hi = -(2**62), 2**62
    lines = [f"1 a {lo}", f"1 a {hi}", "1 a 1", "1 a 2"]
    got = run(lines)
    assert got == run(lines, h2d_compress=False)
    assert got[-1] == ("a", lo + hi + 1 + 2)
