"""H2D delta compression: lossless int64 packing and its demotion path.

The executor ships int64 columns/timestamps as int32 deltas against a
per-batch base (StreamConfig.h2d_compress); a batch whose valid-row span
exceeds int32 must demote that column to raw PERMANENTLY — rebuilding
the jitted step mid-stream — with bit-exact results either way.
"""

import numpy as np

from tpustream import StreamExecutionEnvironment, Tuple2
from tpustream.config import StreamConfig
from tpustream.runtime.sources import ReplaySource


def parse(line: str) -> Tuple2:
    items = line.split(" ")
    return Tuple2(items[1], int(items[2]))


def run(lines, **cfg):
    env = StreamExecutionEnvironment(StreamConfig(batch_size=4, **cfg))
    text = env.add_source(ReplaySource(lines))
    handle = (
        text.map(parse)
        .key_by(0)
        .sum(1)
        .collect()
    )
    env.execute("h2d")
    return [tuple(t) for t in handle.items]


def test_mid_stream_span_overflow_demotes_exactly():
    # batch 1 fits int32 deltas; batch 2 spans > 2^31 (and includes
    # negatives); batch 3 returns to small values — all on the SAME
    # demoted column, exercising the one-time step rebuild
    big = 3 << 31
    lines = (
        ["1 a 5", "1 b 7", "1 a 11", "1 b 13"]
        + [f"1 a {big}", f"1 b {-big}", "1 a 17", "1 b 19"]
        + ["1 a 23", "1 b 29", "1 a 31", "1 b 37"]
    )
    got = run(lines)
    want = run(lines, h2d_compress=False)
    assert got == want
    # the rolling sums are exact through the demotion
    totals = {}
    expect = []
    for line in lines:
        _, k, v = line.split(" ")
        totals[k] = totals.get(k, 0) + int(v)
        expect.append((k, totals[k]))
    assert got == expect


def test_full_range_column_never_compresses():
    # min near -2^62 and max near 2^62 in ONE batch: the span check must
    # not wrap (it is computed in Python ints) and the column ships raw
    lo, hi = -(2**62), 2**62
    lines = [f"1 a {lo}", f"1 a {hi}", "1 a 1", "1 a 2"]
    got = run(lines)
    assert got == run(lines, h2d_compress=False)
    assert got[-1] == ("a", lo + hi + 1 + 2)
